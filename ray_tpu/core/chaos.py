"""Deterministic, seed-driven fault injection for the control plane.

The reference gates releases on fault injection — ``testing_rpc_failure``
in ``ray_config_def.h`` lets any RPC be dropped/delayed by config, and the
chaos test utils SIGKILL raylets and workers mid-run. This module is that
subsystem for this runtime: every process's transport choke point
(``Runtime._flush_box``, ``NodeManager._send``/``_send_direct``,
``Controller._send``) consults one seeded PRNG stream before a message
hits the wire, so a failing run replays from its seed.

Three layers:

- **Message faults** (:class:`ChaosInjector`): per-message-type drop /
  delay / duplicate plus peer severing, decided from
  ``random.Random(f"{seed}:{stream}")`` where ``stream`` names the
  process role (``driver``, ``controller``, ``node``, ``worker:<n>`` —
  workers get a stable spawn index via ``RAY_TPU_CHAOS_ID``). Each
  message consumes a fixed number of draws, so the decision sequence for
  a given (seed, stream, config) is reproducible.
- **Scheduled partitions** (``ChaosConfig.partitions``): a time-indexed
  sever matrix — ``{"start": s, "end": s, "a": role, "b": role}`` cuts
  BOTH directions of the matching link (controller<->node,
  controller<->peer, node<->node) for the window, measured from each
  process's injector creation, then heals. Unlike probabilistic drops a
  partition cuts *everything* on the link, protected types included —
  real partitions don't read message headers. Recovery comes from the
  reliable-delivery layer (``core/reliable.py``) retransmitting the
  critical set after the heal, plus the periodic/reconnect machinery.
- **Duplicate hardening** (:class:`SeqDeduper`): while injection is
  active every injectable payload is stamped with a per-process wire
  sequence number and receivers drop replays — the duplication fault
  continuously proves the at-least-once dedup path (the reliable layer
  runs its own always-on instance against retransmit duplicates).
- **Disk faults** (:class:`DiskFaultInjector`): seeded ``EIO`` /
  ``ENOSPC`` / truncated-read faults on the spill path
  (``native_store.py`` spill writes and restore reads), proving the
  store degrades gracefully — retry with backoff, fall back to re-pull
  from another holder, and only then surface a typed
  ``ObjectLostError``.
- **Process faults** (:class:`ChaosMonkey`): driver/test-side scheduler
  for SIGKILLing workers and node managers mid-task and for controller
  pause/restart, driven by the same seed.

Activation is environment-driven so it propagates to every spawned
process: ``RAY_TPU_CHAOS_SEED=<int>`` turns injection on;
``RAY_TPU_CHAOS_CONFIG=<json>`` tunes probabilities (fields of
:class:`ChaosConfig`). Production runs never touch this module's hot
path — the injector handle is ``None`` and every hook is a single
attribute check.

Determinism note: decision *streams* are bit-reproducible per process;
end-to-end message interleaving still depends on OS scheduling. The
contract chaos tests rely on is that a fixed (seed, config, workload)
exercises the same fault mix and the asserted invariants (no hangs,
typed errors, drained refcounts, no leaked processes) hold on every
replay.
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_SEED = "RAY_TPU_CHAOS_SEED"
ENV_CONFIG = "RAY_TPU_CHAOS_CONFIG"
ENV_STREAM_ID = "RAY_TPU_CHAOS_ID"

#: message types whose loss the runtime cannot recover from — the
#: registration handshake and RPC replies have no retransmit, and
#: RECONNECT is itself the recovery signal. Never injected.
PROTECTED_TYPES = frozenset({"REG", "REGR", "BYE", "RPL", "ERR", "RCN"})

#: default targets for a scalar ``drop_prob``: message types with
#: drop-recovery machinery. PING/HEARTBEAT are periodic; everything
#: else is covered by the reliable-delivery layer's ack/retransmit
#: (core/reliable.py) — which is what finally let the scalar mix cover
#: the whole critical one-way control plane (TASK_DISPATCH, ACTOR_CALL,
#: TASK_ASSIGN, TASK_DONE) instead of a hand-picked safe subset.
#: Request/reply types (SUB, KVO, ...) still need an explicit per-type
#: entry: their drop surfaces as the caller's RpcTimeoutError, which is
#: a worse failure mode to inject by default. SIT/SEF/SCR are the
#: streaming-generator item/EOF/credit reports — covered by the same
#: ack/retransmit layer, so dropping them must still deliver every
#: yielded item exactly once, in order.
#: TEV is the flight-recorder flush (core/events.py): reliably
#: delivered like its peers, and observability loss must never block
#: task progress — exactly the contract chaos drops exercise.
#: MRT is the fleet metric snapshot (core/metrics_plane.py): same
#: contract as TEV, plus reporter-side supersede (drop-oldest) so a
#: sustained 100% drop window bounds the retransmit backlog.
#: RSP is the per-request trace span batch (serve/request_trace.py):
#: same contract as TEV, plus controller-side dedup by
#: (request_id, part, seq) so a dup never yields a double waterfall.
DEFAULT_DROPPABLE = frozenset({"RES", "PUT", "PNG", "HBT",
                               "DSP", "ACL", "ASG", "DON",
                               "SIT", "SEF", "SCR", "TEV", "MRT",
                               "RSP"})


@dataclass
class ChaosConfig:
    """Fault mix for one chaos run. ``drop``/``dup``/``delay`` map a
    message-type name (``"RES"``, ``"PUT"``, ... or ``"*"``) to a
    probability and override the scalar ``*_prob`` defaults.

    ``partitions`` is the scheduled sever matrix: a list of windows
    (seconds from injector creation) in one of two forms:

    - ``{"start": s, "end": s, "a": side, "b": side}`` — cuts every
      message, BOTH directions, on links whose (sender, target) match
      either orientation;
    - ``{"start": s, "end": s, "src": side, "dst": side}`` — an
      **asymmetric one-way window**: only messages FROM a matching
      sender TO a matching target are cut (the reverse direction flows
      normally — the classic half-open link real networks produce).

    A *side* is a role class (``"controller"``, ``"node"``,
    ``"driver"``, ``"worker"``, ``"peer"``, ``"*"``) or a **concrete
    identity**: ``"id:<hexprefix>"`` matches the process's own wire
    identity (sender side) or the target identity (receiver side) by
    hex prefix — so partitions can be keyed to specific node ids
    (:func:`node_identity` renders a NodeID's wire identity) or worker
    ids, not just role classes. Role classes remain coarse: driver and
    worker targets are indistinguishable at the sender (both are
    opaque 28-byte DEALER identities), so either name matches any
    non-node peer; node identities are recognized by their ``b"N"``
    prefix.

    ``latency`` injects **slow links** (not cut links): a list of
    ``{"start": s, "end": s, "src"/"dst" | "a"/"b": side, "prob": p,
    "dist": "uniform"|"exp"|"lognormal", ...params}`` windows; every
    matching message is held for a delay drawn from the distribution
    (``uniform``: ``lo``/``hi``; ``exp``: ``mean``; ``lognormal``:
    ``mu``/``sigma``, in seconds). Draws come from an independent
    seeded stream, so adding latency shifts no drop/dup decisions.
    This is how streaming backpressure is soaked under skew — a slow
    consumer link, not a dead one.

    ``disk``/``disk_fault_prob`` drive the spill-path disk faults
    (ops: ``"spill_write"`` -> EIO/ENOSPC, ``"restore_read"`` ->
    EIO/truncated read), consumed by :class:`DiskFaultInjector`.

    ``maintenance`` schedules **simulated TPU maintenance events**
    against slice providers (consumed by
    ``autoscaler/node_provider.py::FakeSliceProvider``): a list of
    ``{"after_s": t, "slice_index": i, "kind": "maintenance"}``
    entries — ``t`` seconds after provider creation the i-th slice it
    created (0-based, by creation order) receives a drain notice, which
    the SliceManager turns into the full preemption-aware drain
    (notice → draining → placement groups reschedule → release)."""

    seed: int = 0
    drop_prob: float = 0.0            # over DEFAULT_DROPPABLE
    dup_prob: float = 0.0             # over all unprotected types
    delay_prob: float = 0.0           # over all unprotected types
    delay_range_s: Tuple[float, float] = (0.002, 0.1)
    drop: Dict[str, float] = field(default_factory=dict)
    dup: Dict[str, float] = field(default_factory=dict)
    delay: Dict[str, float] = field(default_factory=dict)
    partitions: List[Dict] = field(default_factory=list)
    latency: List[Dict] = field(default_factory=list)
    disk_fault_prob: float = 0.0      # over all spill-path disk ops
    disk: Dict[str, float] = field(default_factory=dict)
    maintenance: List[Dict] = field(default_factory=list)

    @classmethod
    def from_env(cls) -> Optional["ChaosConfig"]:
        seed_raw = os.environ.get(ENV_SEED)
        cfg_raw = os.environ.get(ENV_CONFIG)
        if not seed_raw and not cfg_raw:
            return None
        cfg = cls()
        if cfg_raw:
            try:
                data = json.loads(cfg_raw)
            except ValueError:
                logger.warning("chaos: unparseable %s; injection disabled",
                               ENV_CONFIG)
                return None
            for k, v in data.items():
                if k == "delay_range_s":
                    cfg.delay_range_s = (float(v[0]), float(v[1]))
                elif hasattr(cfg, k):
                    setattr(cfg, k, v)
        if seed_raw:
            try:
                cfg.seed = int(seed_raw)
            except ValueError:
                logger.warning("chaos: non-integer %s=%r; injection "
                               "disabled", ENV_SEED, seed_raw)
                return None
        return cfg

    def env(self) -> Dict[str, str]:
        """Env vars that reproduce this config in a child process."""
        return {
            ENV_SEED: str(self.seed),
            ENV_CONFIG: json.dumps({
                "drop_prob": self.drop_prob, "dup_prob": self.dup_prob,
                "delay_prob": self.delay_prob,
                "delay_range_s": list(self.delay_range_s),
                "drop": self.drop, "dup": self.dup, "delay": self.delay,
                "partitions": self.partitions,
                "latency": self.latency,
                "disk_fault_prob": self.disk_fault_prob,
                "disk": self.disk,
                "maintenance": self.maintenance,
            }),
        }

    def _prob(self, table: Dict[str, float], scalar: float,
              scalar_set: Optional[frozenset], name: str) -> float:
        if name in PROTECTED_TYPES:
            return 0.0
        if name in table:
            return table[name]
        if "*" in table:
            return table["*"]
        if scalar_set is None or name in scalar_set:
            return scalar
        return 0.0

    def drop_p(self, name: str) -> float:
        return self._prob(self.drop, self.drop_prob, DEFAULT_DROPPABLE, name)

    def dup_p(self, name: str) -> float:
        return self._prob(self.dup, self.dup_prob, None, name)

    def delay_p(self, name: str) -> float:
        return self._prob(self.delay, self.delay_prob, None, name)

    def disk_p(self, op: str) -> float:
        return self.disk.get(op, self.disk.get("*", self.disk_fault_prob))


class SeqDeduper:
    """Receiver-side at-least-once filter: drops payloads whose
    ``(sender tag, wire seq)`` was already seen. Bounded LRU — chaos
    duplicates arrive within a handful of messages of the original, so a
    few thousand entries of history is orders of magnitude more than the
    replay window."""

    def __init__(self, cap: int = 8192):
        self._cap = cap
        self._seen: "collections.OrderedDict[tuple, None]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.dropped = 0

    def seen(self, key) -> bool:
        try:
            hash(key)
        except TypeError:
            return False
        with self._lock:
            if key in self._seen:
                self.dropped += 1
                return True
            self._seen[key] = None
            while len(self._seen) > self._cap:
                self._seen.popitem(last=False)
            return False


class ChaosInjector:
    """Per-process message-fault decider. ``plan_send`` is the single
    entry point the transports call; it returns the (possibly empty)
    list of ``(delay_s, payload)`` copies to actually ship."""

    def __init__(self, config: ChaosConfig, stream: str,
                 self_id: Optional[str] = None):
        self.config = config
        self.stream = stream
        self.role = stream.split(":", 1)[0]
        #: this process's wire identity (hex), for concrete-id partition
        #: and latency-link matching (``"id:<hexprefix>"`` sides)
        self.self_id = self_id or ""
        self._rng = random.Random(f"{config.seed}:{stream}")
        #: independent stream for latency-link draws: enabling slow
        #: links must not shift the drop/dup/delay decision sequence
        self._lat_rng = random.Random(f"{config.seed}:{stream}:latency")
        self._lock = threading.Lock()
        #: scheduled-partition clock origin: windows are seconds from
        #: injector creation (process start for spawned processes)
        self._t0 = time.monotonic()
        #: peers cut off (drop everything both directions this process
        #: sees). ``None`` severs the controller link.
        self._severed: set = set()
        #: receiver dedup key: unique per process *instance* (not per
        #: replay — it only needs to distinguish senders at a receiver)
        self._tag = os.urandom(8)
        self._seq = itertools.count(1)
        self.stats: "collections.Counter" = collections.Counter()

    def rng_for(self, name: str) -> random.Random:
        """Independent deterministic stream for an auxiliary consumer
        (e.g. the lease backoff), so its draws don't perturb the message
        decision sequence."""
        return random.Random(f"{self.config.seed}:{self.stream}:{name}")

    # ------------------------------------------------------------- sever
    def sever(self, peer: Optional[bytes]) -> None:
        with self._lock:
            self._severed.add(peer)

    def heal(self, peer: Optional[bytes] = None) -> None:
        with self._lock:
            if peer is None:
                self._severed.clear()
            else:
                self._severed.discard(peer)

    # -------------------------------------------------- partitions
    def _side_matches_role(self, side: str, role: str) -> bool:
        if side.startswith("id:"):
            # concrete identity: match this process's own wire id
            return bool(self.self_id) and \
                self.self_id.startswith(side[3:].lower())
        return side == "*" or side == role or \
            (side in ("driver", "worker", "peer")
             and role in ("driver", "worker"))

    @staticmethod
    def _target_class(target: Optional[bytes]) -> str:
        if target is None:
            return "controller"
        if len(target) == 28 and target[:1] == b"N":
            return "node"
        return "peer"  # worker or driver: indistinguishable identities

    @staticmethod
    def _side_matches_target(side: str, tclass: str,
                             target: Optional[bytes] = None) -> bool:
        if side.startswith("id:"):
            # concrete identity: match the wire target by hex prefix
            return target is not None and \
                target.hex().startswith(side[3:].lower())
        return side == "*" or side == tclass or \
            (side in ("driver", "worker", "peer") and tclass == "peer")

    def _link_matches(self, p: Dict, target: Optional[bytes],
                      tclass: str) -> bool:
        """One window against one (this process -> target) link.
        ``src``/``dst`` windows are ASYMMETRIC: only the named
        direction is affected (this process must match ``src`` as the
        sender). ``a``/``b`` windows match either orientation."""
        if "src" in p or "dst" in p:
            return self._side_matches_role(p.get("src", "*"), self.role) \
                and self._side_matches_target(p.get("dst", "*"), tclass,
                                              target)
        a, b = p.get("a", "*"), p.get("b", "*")
        return (self._side_matches_role(a, self.role)
                and self._side_matches_target(b, tclass, target)) or \
               (self._side_matches_role(b, self.role)
                and self._side_matches_target(a, tclass, target))

    def _partitioned(self, target: Optional[bytes], now: float) -> bool:
        """True when a scheduled partition window currently severs the
        (this role -> target) link. Pure time check — consumes no RNG
        draws, so adding partitions to a config shifts no other fault
        decisions."""
        t = now - self._t0
        tclass = self._target_class(target)
        for p in self.config.partitions:
            if not (p.get("start", 0.0) <= t < p.get("end", float("inf"))):
                continue
            if self._link_matches(p, target, tclass):
                return True
        return False

    def _link_delay(self, target: Optional[bytes], now: float) -> float:
        """Latency-distribution injection: extra delay for this message
        from matching slow-link windows (``ChaosConfig.latency``).
        Draws come from the dedicated ``:latency`` stream."""
        if not self.config.latency:
            return 0.0
        t = now - self._t0
        tclass = self._target_class(target)
        total = 0.0
        for p in self.config.latency:
            if not (p.get("start", 0.0) <= t < p.get("end", float("inf"))):
                continue
            if not self._link_matches(p, target, tclass):
                continue
            with self._lock:
                if self._lat_rng.random() >= p.get("prob", 1.0):
                    continue
                dist = p.get("dist", "uniform")
                if dist == "exp":
                    d = self._lat_rng.expovariate(
                        1.0 / max(1e-6, float(p.get("mean", 0.05))))
                elif dist == "lognormal":
                    d = self._lat_rng.lognormvariate(
                        float(p.get("mu", -3.5)),
                        float(p.get("sigma", 0.5)))
                else:
                    lo = float(p.get("lo", 0.01))
                    hi = float(p.get("hi", max(0.05, lo)))
                    d = lo + self._lat_rng.random() * (hi - lo)
            total += min(d, float(p.get("cap", 5.0)))
        return total

    # -------------------------------------------------------------- plan
    def plan_send(self, target: Optional[bytes], mtype: bytes,
                  payload: Any) -> List[Tuple[float, Any]]:
        """Decide the fate of one outgoing message. ``target`` is the
        peer identity (``None`` = the controller link). Returns
        ``[(delay_s, payload), ...]``: empty list = dropped, two entries
        = duplicated. Injectable dict payloads are stamped with a wire
        sequence number for receiver-side dedup."""
        name = mtype.decode("ascii", "replace")
        if isinstance(payload, dict) and \
                payload.pop("__chaos_delayed__", None):
            # second pass of a message we already delayed: it was
            # decided once — ship it now. Without this, always-on
            # latency links (prob 1.0) would re-delay on every re-entry
            # and the message would never reach the wire.
            self.stats[("delayed_ship", name)] += 1
            return [(0.0, payload)]
        now = time.monotonic()
        # scheduled partitions cut EVERYTHING on the link, protected
        # types included — a real partition doesn't read headers
        if self.config.partitions and self._partitioned(target, now):
            self.stats[("partition", name)] += 1
            return []
        # slow links delay EVERYTHING too (a congested path doesn't
        # read headers either), protected types included — unlike a cut
        # this is always recoverable by waiting
        link_delay = self._link_delay(target, now)
        if link_delay > 0.0:
            self.stats[("latency", name)] += 1
        if name in PROTECTED_TYPES:
            if link_delay > 0.0 and isinstance(payload, dict):
                payload = dict(payload, __chaos_delayed__=True)
            return [(link_delay, payload)]
        cfg = self.config
        with self._lock:
            if self._severed and (target in self._severed):
                self.stats[("sever", name)] += 1
                return []
            # fixed draw count per message keeps the stream replayable
            r_drop = self._rng.random()
            r_dup = self._rng.random()
            r_delay = self._rng.random()
            r_amount = self._rng.random()
            n = next(self._seq)
        if r_drop < cfg.drop_p(name):
            self.stats[("drop", name)] += 1
            return []
        if isinstance(payload, dict):
            payload = dict(payload, __wseq__=(self._tag, n))
        lo, hi = cfg.delay_range_s
        delay = lo + r_amount * (hi - lo) \
            if r_delay < cfg.delay_p(name) else 0.0
        if delay > 0.0:
            self.stats[("delay", name)] += 1
        delay += link_delay
        delayed = payload
        if delay > 0.0 and isinstance(payload, dict):
            # delayed copies re-enter the transport's send path via a
            # timer; the marker makes the second pass ship-only (the
            # immediate dup below stays unmarked — it never re-enters)
            delayed = dict(payload, __chaos_delayed__=True)
        out = [(delay, delayed)]
        if isinstance(payload, dict) and r_dup < cfg.dup_p(name):
            # the copy carries the SAME wire seq: receivers must drop
            # it. It must be a DISTINCT dict object though: both copies
            # can coalesce into one MSG_BATCH, where pickle's memo
            # would collapse one shared object into one deserialized
            # dict — the first dispatch pops the __wseq__/__rseq__
            # dedup stamps and the second copy then passes both dedups
            # (double-handling instead of a deduped duplicate).
            self.stats[("dup", name)] += 1
            out.append((0.0, dict(payload)))
        return out


def node_identity(node_id_b: bytes) -> bytes:
    """A node manager's wire identity for a given NodeID binary — lets
    tests key partition/latency matrices to concrete nodes
    (``"id:" + node_identity(nid).hex()``)."""
    return b"N" + node_id_b[:27]


def maybe_injector(role: str,
                   self_id: Optional[bytes] = None
                   ) -> Optional[ChaosInjector]:
    """The per-process activation hook: returns an injector when chaos
    env vars are set, else ``None`` (the common case — callers keep a
    ``None`` handle and skip every chaos branch). ``self_id`` is the
    process's wire identity, for concrete-id (``"id:<hexprefix>"``)
    partition/latency matching."""
    cfg = ChaosConfig.from_env()
    if cfg is None:
        return None
    sid = os.environ.get(ENV_STREAM_ID, "")
    stream = f"{role}:{sid}" if sid else role
    inj = ChaosInjector(cfg, stream,
                        self_id=self_id.hex() if self_id else None)
    logger.warning("chaos: fault injection ACTIVE (seed=%d stream=%s)",
                   cfg.seed, stream)
    return inj


def check_dedup(dedup: Optional[SeqDeduper], payload: Any) -> bool:
    """Receiver-side hook: pops the wire seq stamp (and the delayed-ship
    marker, for transports whose parked sends go straight to the wire)
    and returns True when the payload is a duplicate that must be
    discarded."""
    if dedup is None or not isinstance(payload, dict):
        return False
    payload.pop("__chaos_delayed__", None)
    key = payload.pop("__wseq__", None)
    return key is not None and dedup.seen(key)


class DiskFaultInjector:
    """Seeded fault decider for the spill path's disk I/O
    (``native_store.py``). One deterministic stream per process,
    independent of the message-fault draws (``:disk`` suffix), so
    enabling disk faults shifts no message decisions.

    Ops and fault kinds:

    - ``spill_write``: ``"eio"`` | ``"enospc"`` — the spill write is
      refused; the store keeps the object resident (it is still the
      only copy) and retries on a later sweep.
    - ``restore_read``: ``"eio"`` (transient — the store reports
      ``"retry"`` until a strike cap, then declares the local backing
      copy lost) | ``"truncate"`` (a torn file: immediately lost).
    """

    def __init__(self, config: ChaosConfig, stream: str):
        self.config = config
        self.stream = stream
        self._rng = random.Random(f"{config.seed}:{stream}:disk")
        self._lock = threading.Lock()
        self.stats: "collections.Counter" = collections.Counter()

    def fault(self, op: str) -> Optional[str]:
        """Draw the fate of one disk operation: None (healthy) or a
        fault kind. Fixed two draws per call keeps the stream
        replayable."""
        p = self.config.disk_p(op)
        with self._lock:
            r = self._rng.random()
            r_kind = self._rng.random()
        if p <= 0.0 or r >= p:
            return None
        if op == "spill_write":
            kind = "enospc" if r_kind < 0.33 else "eio"
        else:
            kind = "truncate" if r_kind < 0.25 else "eio"
        self.stats[(op, kind)] += 1
        return kind


def maybe_disk_injector(role: str) -> Optional[DiskFaultInjector]:
    """Spill-path activation hook (mirrors :func:`maybe_injector`):
    returns a disk-fault injector when chaos env vars are set with a
    non-zero disk fault mix, else None."""
    cfg = ChaosConfig.from_env()
    if cfg is None or (cfg.disk_fault_prob <= 0.0 and not cfg.disk):
        return None
    sid = os.environ.get(ENV_STREAM_ID, "")
    stream = f"{role}:{sid}" if sid else role
    inj = DiskFaultInjector(cfg, stream)
    logger.warning("chaos: disk-fault injection ACTIVE (seed=%d "
                   "stream=%s)", cfg.seed, stream)
    return inj


class ChaosMonkey:
    """Process-level fault scheduler for tests: SIGKILLs workers and
    node managers mid-task and pauses/restarts the controller, all
    ordered by one seeded PRNG (reference: the chaos/node-killer test
    utils). Operates on the in-process head (``ray_tpu.api._head``) of
    the calling driver."""

    def __init__(self, seed: int, head=None):
        self.rng = random.Random(f"{seed}:monkey")
        self._head = head
        self.log: List[tuple] = []

    def _get_head(self):
        if self._head is not None:
            return self._head
        import ray_tpu.api as api
        return api._head

    # ------------------------------------------------------------ workers
    def worker_pids(self) -> Dict[bytes, int]:
        node = self._get_head().node
        with node._workers_lock:
            return {ident: proc.pid
                    for ident, proc in node.workers.items()}

    def kill_random_worker(self, exclude: Tuple[int, ...] = ()
                           ) -> Optional[int]:
        """SIGKILL one currently-registered worker of the head node,
        chosen deterministically; returns its pid (None if no
        candidates)."""
        pids = self.worker_pids()
        candidates = sorted(p for p in pids.values() if p not in exclude)
        if not candidates:
            return None
        victim = self.rng.choice(candidates)
        self.log.append(("kill_worker", victim))
        try:
            os.kill(victim, signal.SIGKILL)
        except ProcessLookupError:
            pass
        return victim

    def kill_node_proc(self, proc) -> None:
        """SIGKILL a standalone node-manager process (a
        ``cluster_utils`` node's subprocess)."""
        self.log.append(("kill_node", proc.pid))
        try:
            proc.kill()
        except Exception:
            pass

    # --------------------------------------------------------- controller
    def restart_controller(self):
        """kill -9 equivalent for the in-process controller: abandon it
        without any state flush (durability must come from the WAL
        alone) and start a fresh one on the same session."""
        from ray_tpu.core.controller import Controller
        head = self._get_head()
        old = head.controller
        self.log.append(("restart_controller",))
        old._shutdown.set()
        rel = getattr(old, "_reliable", None)
        if rel is not None:
            # a kill -9 takes the retransmit thread with it too
            rel.stop()
        try:
            old._wake_send.send(b"")
        except Exception:
            pass
        if old._thread is not None:
            old._thread.join(timeout=10)
        head.controller = Controller(head.session_dir, old.config)
        head.controller.start()
        return head.controller

    def pause_controller(self, seconds: float) -> threading.Thread:
        """Wedge the controller event loop for ``seconds`` (GC-pause /
        overload simulation). Returns the thread holding the loop."""
        head = self._get_head()
        self.log.append(("pause_controller", seconds))

        def hold():
            try:
                head.controller.call_on_loop(
                    lambda: time.sleep(seconds), timeout=seconds + 30.0)
            except Exception:
                pass

        t = threading.Thread(target=hold, name="chaos-pause", daemon=True)
        t.start()
        return t
