"""Process-global worker/runtime handle (reference:
``python/ray/_private/worker.py`` module-level ``global_worker``)."""

from __future__ import annotations

from typing import Optional

_worker = None


def set_global_worker(worker) -> None:
    global _worker
    _worker = worker


def try_global_worker():
    return _worker


def global_worker():
    if _worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first.")
    return _worker
