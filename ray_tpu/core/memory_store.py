"""In-process memory store for small objects and pending futures.

Equivalent of the reference's ``CoreWorkerMemoryStore``
(``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``):
holds small/direct task returns and unresolved futures; ``get`` blocks until
the object arrives or errors. Objects above the inline threshold live in the
shared-memory store instead (dual-path ``GetImpl``, memory_store.cc).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ray_tpu.core.ids import ObjectID


class _Entry:
    __slots__ = ("value", "error", "ready")

    def __init__(self):
        self.value = None
        self.error: Optional[BaseException] = None
        self.ready = False


class InProcessStore:
    def __init__(self):
        self._lock = threading.Condition()
        self._objects: Dict[ObjectID, _Entry] = {}
        self._callbacks: Dict[ObjectID, List[Callable]] = {}

    def put(self, object_id: ObjectID, value, error: Optional[BaseException] = None,
            force: bool = False) -> None:
        with self._lock:
            e = self._objects.setdefault(object_id, _Entry())
            if e.ready and not force:
                return  # idempotent (retries may double-complete)
            e.value = value
            e.error = error
            e.ready = True
            callbacks = self._callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb(value, error)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._objects.get(object_id)
            return e is not None and e.ready

    def get(self, object_id: ObjectID, timeout: Optional[float] = None):
        """Blocks; returns value or raises the stored error."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                e = self._objects.get(object_id)
                if e is not None and e.ready:
                    if e.error is not None:
                        raise e.error
                    return e.value
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    from ray_tpu.exceptions import GetTimeoutError
                    raise GetTimeoutError(f"timed out waiting for {object_id}")
                if not self._lock.wait(timeout=remaining if remaining is None or remaining < 0.2 else 0.2):
                    pass

    def try_get(self, object_id: ObjectID):
        """Non-blocking; returns (found, value_or_error_raised)."""
        with self._lock:
            e = self._objects.get(object_id)
            if e is None or not e.ready:
                return False, None
            if e.error is not None:
                raise e.error
            return True, e.value

    def on_ready(self, object_id: ObjectID, callback: Callable) -> None:
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None and e.ready:
                value, error = e.value, e.error
            else:
                self._callbacks.setdefault(object_id, []).append(callback)
                return
        callback(value, error)

    def remove_callback(self, object_id: ObjectID, callback: Callable) -> None:
        """Unregister an ``on_ready`` hook (waiters with expired timeouts)."""
        with self._lock:
            lst = self._callbacks.get(object_id)
            if lst is None:
                return
            try:
                lst.remove(callback)
            except ValueError:
                pass
            if not lst:
                self._callbacks.pop(object_id, None)

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._objects.pop(object_id, None)
            self._callbacks.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
