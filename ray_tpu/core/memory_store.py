"""In-process memory store for small objects and pending futures.

Equivalent of the reference's ``CoreWorkerMemoryStore``
(``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``):
holds small/direct task returns and unresolved futures; ``get`` blocks until
the object arrives or errors. Objects above the inline threshold live in the
shared-memory store instead (dual-path ``GetImpl``, memory_store.cc).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ray_tpu.core.ids import ObjectID


class WeakExpired:
    """Sentinel handed to on_ready callbacks whose weak-cached value was
    collected: the receiver re-materializes from shared memory."""

    __slots__ = ()


class WeakCacheExpired(Exception):
    """A blocking get hit a weak cache entry whose value was collected:
    the object still exists in shm — the caller re-materializes instead
    of treating this as a timeout or failure."""


_WEAK_EXPIRED = WeakExpired()


class _Entry:
    __slots__ = ("value", "error", "ready", "weak")

    def __init__(self):
        self.value = None
        self.error: Optional[BaseException] = None
        self.ready = False
        self.weak = False

    def live_value(self):
        """(alive, value): weak entries whose target was collected are
        dead — the caller re-materializes from shm."""
        if not self.weak:
            return True, self.value
        v = self.value()
        return (v is not None), v


class InProcessStore:
    def __init__(self):
        self._lock = threading.Condition()
        self._objects: Dict[ObjectID, _Entry] = {}
        self._callbacks: Dict[ObjectID, List[Callable]] = {}

    def put(self, object_id: ObjectID, value, error: Optional[BaseException] = None,
            force: bool = False, weak: bool = False) -> None:
        """``weak=True`` caches a weakref: large shm-materialized values
        must not be pinned by the cache beyond their user's lifetime —
        the reader-ledger release (and therefore extent reuse) is tied
        to the value's GC (reference: plasma buffers are pinned by the
        client only while Python holds them)."""
        import weakref
        if weak:
            try:
                stored = weakref.ref(value)
            except TypeError:
                stored, weak = value, False
        else:
            stored = value
        with self._lock:
            e = self._objects.setdefault(object_id, _Entry())
            if e.ready and not force:
                return  # idempotent (retries may double-complete)
            e.value = stored
            e.error = error
            e.ready = True
            e.weak = weak
            callbacks = self._callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb(value, error)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._objects.get(object_id)
            if e is None or not e.ready:
                return False
            alive, _ = e.live_value()
            if not alive:
                del self._objects[object_id]
                return False
            return True

    def get(self, object_id: ObjectID, timeout: Optional[float] = None):
        """Blocks; returns value or raises the stored error."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                e = self._objects.get(object_id)
                if e is not None and e.ready:
                    if e.error is not None:
                        raise e.error
                    alive, v = e.live_value()
                    if not alive:
                        # collected weak value: the caller re-derives it
                        # from shm via the meta path
                        del self._objects[object_id]
                        raise WeakCacheExpired(str(object_id))
                    return v
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    from ray_tpu.exceptions import GetTimeoutError
                    raise GetTimeoutError(f"timed out waiting for {object_id}")
                if not self._lock.wait(timeout=remaining if remaining is None or remaining < 0.2 else 0.2):
                    pass

    def try_get(self, object_id: ObjectID):
        """Non-blocking; returns (found, value_or_error_raised)."""
        with self._lock:
            e = self._objects.get(object_id)
            if e is None or not e.ready:
                return False, None
            if e.error is not None:
                raise e.error
            alive, v = e.live_value()
            if not alive:
                del self._objects[object_id]
                return False, None
            return True, v

    def on_ready(self, object_id: ObjectID, callback: Callable) -> None:
        with self._lock:
            e = self._objects.get(object_id)
            if e is not None and e.ready:
                alive, value = e.live_value()
                error = e.error
                if not alive and error is None:
                    # collected weak value: completion already happened;
                    # the receiver re-derives the value from shm
                    del self._objects[object_id]
                    value = _WEAK_EXPIRED
            else:
                self._callbacks.setdefault(object_id, []).append(callback)
                return
        callback(value, error)

    def remove_callback(self, object_id: ObjectID, callback: Callable) -> None:
        """Unregister an ``on_ready`` hook (waiters with expired timeouts)."""
        with self._lock:
            lst = self._callbacks.get(object_id)
            if lst is None:
                return
            try:
                lst.remove(callback)
            except ValueError:
                pass
            if not lst:
                self._callbacks.pop(object_id, None)

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._objects.pop(object_id, None)
            self._callbacks.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
