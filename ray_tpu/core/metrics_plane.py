"""Cluster-wide metrics plane: fleet aggregation of process snapshots.

Reference: the per-node OpenCensus pipeline behind ``metric_defs.cc`` —
every Ray process exports to its node's Prometheus endpoint and an
external Prometheus server does the fleet math. Here the controller IS
the aggregation point: every process periodically ships a
``METRIC_REPORT`` (MRT) snapshot of its whole metric registry
(``util/metrics.py::export_snapshot`` — cumulative counters, last-value
gauges, histogram bucket vectors) over the PR-2 reliable layer
(exactly-once-effect, fire-and-forget for the producer), and this
module merges them keyed ``(node, pid, role)`` into bounded
fixed-interval time-series rings per ``(metric, labelset)``.

Derived series come straight from the rings:

- **per-window rates** for counters (fleet tokens/s, retransmits/s)
  from slot-to-slot deltas, reset-corrected so a restarted process
  (counter back to 0) adds instead of subtracting;
- **fleet histogram quantiles** from summed bucket *deltas* across
  origins (fleet TTFT p50/p99 — the classic
  ``histogram_quantile(sum by (le) (rate(...)))`` shape);
- **latest-value fleet gauges** (queue depths, occupancy, bubble
  fraction, MFU).

Surfaces: one cluster ``/metrics`` Prometheus endpoint on the dashboard
head (origin labels on every sample), the ``/api/v0/metrics`` catalog +
``/api/v0/metrics/query`` JSON API, Chrome-trace counter tracks for
``/timeline``, and the ``ray-tpu top`` fleet view (``tools/top.py``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: labels stamped on every aggregated sample naming the origin process
ORIGIN_LABELS = ("node", "pid", "role")

#: histogram quantile aggregations accepted by :meth:`MetricsPlane.query`
_QUANTILE_AGGS = {"p50": 0.5, "p90": 0.9, "p95": 0.95, "p99": 0.99}


def bucket_quantile(bounds: Sequence[float], counts: Sequence[float],
                    q: float) -> Optional[float]:
    """Quantile from a histogram bucket-count vector.

    ``counts`` has ``len(bounds) + 1`` entries (the last is the +Inf
    overflow bucket). Linear interpolation inside the winning bucket,
    Prometheus ``histogram_quantile`` style; the +Inf bucket clamps to
    the highest finite bound. Returns None for an empty histogram."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = float(sum(counts))
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c > 0:
            if i >= len(bounds):
                return float(bounds[-1]) if bounds else None
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            frac = (rank - (cum - c)) / c
            return lo + (hi - lo) * frac
    return float(bounds[-1]) if bounds else None


class SeriesRing:
    """Bounded fixed-interval time-series ring.

    Samples land in the slot ``floor(ts / interval)`` (last write wins
    within a slot — snapshots are cumulative, the freshest supersedes);
    only the most recent ``slots`` slots are kept. Out-of-order
    arrivals (a retransmitted older report) write into their own older
    slot and never corrupt newer ones."""

    __slots__ = ("interval", "slots", "_d")

    def __init__(self, interval_s: float = 1.0, slots: int = 600):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.interval = float(interval_s)
        self.slots = int(slots)
        self._d: Dict[int, Any] = {}

    def put(self, ts: float, value: Any) -> None:
        self._d[int(ts // self.interval)] = value
        while len(self._d) > self.slots:
            del self._d[min(self._d)]

    def __len__(self) -> int:
        return len(self._d)

    def points(self, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, Any]]:
        """Sorted ``(slot_start_ts, value)`` pairs, optionally limited
        to the trailing ``window_s`` seconds before ``now``."""
        items = sorted(self._d.items())
        if window_s is not None:
            if now is None:
                import time
                now = time.time()
            lo = (now - window_s) // self.interval
            items = [kv for kv in items if kv[0] >= lo]
        return [(k * self.interval, v) for k, v in items]

    def latest(self) -> Optional[Tuple[float, Any]]:
        if not self._d:
            return None
        k = max(self._d)
        return (k * self.interval, self._d[k])


class _Series:
    """One origin's one labelset of one metric: the ring plus the
    counter-reset correction state (a restarted process starts its
    cumulative counters back at zero — the merge must treat that as
    continuation, not a negative rate)."""

    __slots__ = ("kind", "labels", "origin", "ring",
                 "last_raw", "base", "last_sum_raw", "sum_base")

    def __init__(self, kind: str, labels: Tuple, origin: Tuple,
                 interval_s: float, slots: int):
        self.kind = kind
        self.labels = labels              # ((k, v), ...) incl. origin
        self.origin = origin              # (node, pid, role)
        self.ring = SeriesRing(interval_s, slots)
        self.last_raw: Any = None         # float | List[float]
        self.base: Any = None
        self.last_sum_raw = 0.0
        self.sum_base = 0.0

    def update_counter(self, ts: float, raw: float) -> None:
        if self.last_raw is None:
            self.base = 0.0
        elif raw < self.last_raw:
            self.base += self.last_raw    # process restarted: carry on
        self.last_raw = raw
        self.ring.put(ts, self.base + raw)

    def update_gauge(self, ts: float, raw: float) -> None:
        self.ring.put(ts, float(raw))

    def update_histogram(self, ts: float, counts: List[float],
                         total: float) -> None:
        if self.last_raw is None or len(self.last_raw) != len(counts):
            self.base = [0.0] * len(counts)
            self.sum_base = 0.0
        elif sum(counts) < sum(self.last_raw):
            self.base = [b + r for b, r in zip(self.base, self.last_raw)]
            self.sum_base += self.last_sum_raw
        self.last_raw = list(counts)
        self.last_sum_raw = float(total)
        self.ring.put(ts, (tuple(b + c for b, c in
                                 zip(self.base, counts)),
                           self.sum_base + total))


def _label_tuple(pairs: Iterable) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in pairs))


class MetricsPlane:
    """Controller-side fleet aggregator. Thread-safe: MRT batches land
    on the controller loop thread, the controller's own reporter fires
    from the health thread, and the dashboard's HTTP threads query."""

    #: hard cap on distinct (metric, labelset, origin) series; overflow
    #: is counted (``stats["series_dropped"]``), never unbounded memory
    MAX_SERIES = 8192

    def __init__(self, interval_s: float = 1.0, slots: int = 600):
        self._lock = threading.Lock()
        self.interval_s = float(interval_s)
        self.slots = int(slots)
        #: (node, pid, role) -> {"seq": int, "ts": float}
        self._origins: Dict[Tuple, Dict] = {}
        #: metric name -> {"type", "desc", "bounds"}
        self._meta: Dict[str, Dict] = {}
        #: (name, labels) -> _Series
        self._series: Dict[Tuple, _Series] = {}
        self.stats: Dict[str, int] = {"reports": 0, "stale": 0,
                                      "series_dropped": 0}

    @classmethod
    def from_config(cls, config) -> "MetricsPlane":
        return cls(
            interval_s=getattr(config, "metrics_ring_interval_s", 1.0),
            slots=getattr(config, "metrics_ring_slots", 600))

    # ---------------------------------------------------------- ingest
    def ingest(self, payload: Dict) -> bool:
        """Merge one METRIC_REPORT payload. Returns False for stale or
        malformed reports (seq at or below the origin's last seen —
        exactly-once-effect even if the reliable layer's dedup missed a
        replay, e.g. across a controller restart)."""
        try:
            origin = payload["origin"]
            okey = (str(origin.get("node")), int(origin.get("pid", 0)),
                    str(origin.get("role")))
            seq = int(payload.get("seq", 0))
            ts = float(payload.get("ts", 0.0))
            metrics = payload.get("metrics") or []
        except Exception:
            return False
        opairs = tuple(zip(ORIGIN_LABELS, map(str, okey)))
        with self._lock:
            ent = self._origins.get(okey)
            if ent is not None and seq <= ent["seq"]:
                self.stats["stale"] += 1
                return False
            self._origins[okey] = {"seq": seq, "ts": ts}
            self.stats["reports"] += 1
            for m in metrics:
                try:
                    self._ingest_metric_locked(m, okey, opairs, ts)
                except Exception:
                    continue
        return True

    def _ingest_metric_locked(self, m: Dict, okey: Tuple,
                              opairs: Tuple, ts: float) -> None:
        name, kind = m["name"], m["type"]
        meta = self._meta.setdefault(
            name, {"type": kind, "desc": m.get("desc", ""),
                   "bounds": m.get("bounds")})
        if m.get("desc") and not meta["desc"]:
            meta["desc"] = m["desc"]
        for sample in m.get("samples", ()):
            labels = _label_tuple(list(sample[0]) + list(opairs))
            skey = (name, labels)
            s = self._series.get(skey)
            if s is None:
                if len(self._series) >= self.MAX_SERIES:
                    self.stats["series_dropped"] += 1
                    continue
                s = self._series[skey] = _Series(
                    kind, labels, okey, self.interval_s, self.slots)
            if kind == "counter":
                s.update_counter(ts, float(sample[1]))
            elif kind == "gauge":
                s.update_gauge(ts, float(sample[1]))
            elif kind == "histogram":
                s.update_histogram(ts, [float(c) for c in sample[1]],
                                   float(sample[2]))

    # --------------------------------------------------------- queries
    def catalog(self) -> List[Dict]:
        """One row per metric name: type, help, series count, origins
        contributing, and (for scalars) the fleet total/latest — the
        ``/api/v0/metrics`` payload."""
        with self._lock:
            per_name: Dict[str, List[_Series]] = {}
            for (name, _), s in self._series.items():
                per_name.setdefault(name, []).append(s)
            rows = []
            for name in sorted(self._meta):
                meta = self._meta[name]
                series = per_name.get(name, [])
                origins = sorted({s.origin for s in series})
                row = {"name": name, "type": meta["type"],
                       "description": meta["desc"],
                       "series": len(series),
                       "origins": [list(o) for o in origins]}
                if meta["type"] in ("counter", "gauge"):
                    latest = [s.ring.latest() for s in series]
                    vals = [v for v in latest if v is not None]
                    if vals:
                        row["fleet_total" if meta["type"] == "counter"
                            else "fleet_sum"] = sum(v for _, v in vals)
                rows.append(row)
            return rows

    def latest_samples(self, name: str) -> List[Dict]:
        """Every series' freshest value for one metric (origin labels
        included)."""
        out = []
        with self._lock:
            for (n, labels), s in self._series.items():
                if n != name:
                    continue
                latest = s.ring.latest()
                if latest is None:
                    continue
                ts, v = latest
                row = {"labels": dict(labels), "ts": ts}
                if s.kind == "histogram":
                    row["counts"] = list(v[0])
                    row["sum"] = v[1]
                    row["count"] = sum(v[0])
                else:
                    row["value"] = v
                out.append(row)
        out.sort(key=lambda r: sorted(r["labels"].items()))
        return out

    def query(self, name: str, window_s: float = 60.0,
              agg: Optional[str] = None,
              now: Optional[float] = None) -> Dict:
        """Fleet-aggregated time series for one metric over the
        trailing window.

        ``agg`` by metric type — counters: ``rate`` (default; summed
        per-slot delta / slot width) or ``total``; gauges: ``sum``
        (default) / ``avg`` / ``max`` / ``min``; histograms: ``p50`` /
        ``p90`` / ``p95`` / ``p99`` (bucket-delta quantiles), ``rate``
        (observations/s) or ``mean``. Returns ``{"name", "agg",
        "interval_s", "points": [[ts, value], ...]}``."""
        if now is None:
            import time
            now = time.time()
        with self._lock:
            meta = self._meta.get(name)
            if meta is None:
                return {"name": name, "agg": agg, "error": "unknown",
                        "interval_s": self.interval_s, "points": []}
            kind = meta["type"]
            if agg is None:
                agg = {"counter": "rate", "gauge": "sum",
                       "histogram": "p50"}[kind]
            series = [s for (n, _), s in self._series.items()
                      if n == name]
            # one extra leading slot so the first windowed slot has a
            # predecessor to delta against
            pts = [s.ring.points(window_s + self.interval_s, now)
                   for s in series]
        slot_vals: Dict[float, List[float]] = {}
        for p in pts:
            if kind == "gauge":
                for ts, v in p:
                    slot_vals.setdefault(ts, []).append(v)
                continue
            for (ts0, v0), (ts1, v1) in zip(p, p[1:]):
                dt = ts1 - ts0
                if dt <= 0:
                    continue
                if kind == "counter":
                    val = {"rate": (v1 - v0) / dt, "total": v1}[
                        agg if agg in ("rate", "total") else "rate"]
                    slot_vals.setdefault(ts1, []).append(val)
                else:  # histogram: per-slot bucket/sum deltas
                    dc = [b - a for a, b in zip(v0[0], v1[0])]
                    dsum = v1[1] - v0[1]
                    slot_vals.setdefault(ts1, []).append(
                        (dc, dsum, dt))  # type: ignore[arg-type]
        lo = now - window_s
        points: List[List[float]] = []
        for ts in sorted(slot_vals):
            if ts < lo:
                continue
            vals = slot_vals[ts]
            if kind == "histogram":
                merged = None
                total_sum = 0.0
                dt = self.interval_s
                for dc, dsum, d in vals:  # type: ignore[misc]
                    merged = dc if merged is None else \
                        [a + b for a, b in zip(merged, dc)]
                    total_sum += dsum
                    dt = d
                n_obs = sum(merged) if merged else 0.0
                if agg in _QUANTILE_AGGS:
                    v = bucket_quantile(meta.get("bounds") or [],
                                        merged or [],
                                        _QUANTILE_AGGS[agg])
                    if v is None:
                        continue
                elif agg == "rate":
                    v = n_obs / dt
                elif agg == "mean":
                    if n_obs <= 0:
                        continue
                    v = total_sum / n_obs
                else:
                    raise ValueError(f"bad histogram agg {agg!r}")
                points.append([ts, v])
                continue
            if kind == "gauge":
                if agg == "sum":
                    v = sum(vals)
                elif agg == "avg":
                    v = sum(vals) / len(vals)
                elif agg == "max":
                    v = max(vals)
                elif agg == "min":
                    v = min(vals)
                else:
                    raise ValueError(f"bad gauge agg {agg!r}")
            else:
                v = sum(vals)
            points.append([ts, v])
        return {"name": name, "agg": agg,
                "interval_s": self.interval_s, "points": points}

    # ------------------------------------------------- Prometheus text
    def prometheus_text(self) -> str:
        """The whole fleet in Prometheus exposition format — the single
        cluster scrape target. Every sample carries its origin labels
        (``node``/``pid``/``role``), so per-process drill-down is a
        label matcher away."""
        from ray_tpu.util.metrics import _fmt_labels
        with self._lock:
            per_name: Dict[str, List[_Series]] = {}
            for (name, _), s in self._series.items():
                per_name.setdefault(name, []).append(s)
            lines: List[str] = []
            for name in sorted(per_name):
                meta = self._meta.get(name) or {}
                if meta.get("desc"):
                    lines.append(f"# HELP {name} {meta['desc']}")
                lines.append(
                    f"# TYPE {name} {meta.get('type', 'untyped')}")
                for s in sorted(per_name[name],
                                key=lambda s: s.labels):
                    latest = s.ring.latest()
                    if latest is None:
                        continue
                    _, v = latest
                    if s.kind == "histogram":
                        bounds = meta.get("bounds") or []
                        cum = 0.0
                        for bound, c in zip(bounds, v[0]):
                            cum += c
                            lines.append(
                                f"{name}_bucket"
                                f"{_fmt_labels(s.labels, le=bound)} "
                                f"{cum}")
                        cum += v[0][-1] if len(v[0]) > len(bounds) \
                            else 0.0
                        lines.append(
                            f"{name}_bucket"
                            f'{_fmt_labels(s.labels, le="+Inf")} {cum}')
                        lines.append(
                            f"{name}_count{_fmt_labels(s.labels)} "
                            f"{cum}")
                        lines.append(
                            f"{name}_sum{_fmt_labels(s.labels)} "
                            f"{v[1]}")
                    else:
                        lines.append(
                            f"{name}{_fmt_labels(s.labels)} {v}")
        return "\n".join(lines) + "\n"

    # ------------------------------------------- Chrome counter tracks
    def chrome_counters(self, names: Optional[Sequence[str]] = None,
                        window_s: Optional[float] = None,
                        now: Optional[float] = None) -> List[Dict]:
        """Chrome-trace counter events (``"ph": "C"``) for the
        dashboard ``/timeline``: tokens/s, queue depth, occupancy et al
        rendered as curves alongside the flight recorder's spans. One
        counter track per (metric, origin process); counter metrics
        plot their per-slot rate, gauges their value."""
        if names is None:
            names = DEFAULT_TIMELINE_COUNTERS
        out: List[Dict] = []
        with self._lock:
            series = [((n, labels), s)
                      for (n, labels), s in self._series.items()
                      if n in names and s.kind != "histogram"]
            pts = [(key, s.kind, s.origin,
                    s.ring.points(window_s, now)) for key, s in series]
        for (name, _labels), kind, origin, p in pts:
            proc = f"{origin[2]}:{origin[1]}"
            track = name
            if kind == "counter":
                track += "/s"
                p = [(ts1, (v1 - v0) / (ts1 - ts0))
                     for (ts0, v0), (ts1, v1) in zip(p, p[1:])
                     if ts1 > ts0]
            for ts, v in p:
                out.append({"name": track, "cat": "metric", "ph": "C",
                            "ts": ts * 1e6, "pid": 0, "tid": 0,
                            "proc": proc,
                            "args": {"value": round(float(v), 4)}})
        return out

    # ----------------------------------------------------- fleet view
    def _origin_latest(self, name: str) -> Dict[Tuple, float]:
        """origin -> summed latest value across that origin's labelsets
        of ``name`` (lock held by caller)."""
        out: Dict[Tuple, float] = {}
        for (n, _), s in self._series.items():
            if n != name or s.kind == "histogram":
                continue
            latest = s.ring.latest()
            if latest is not None:
                out[s.origin] = out.get(s.origin, 0.0) + latest[1]
        return out

    def _origin_rate(self, name: str, window_s: float,
                     now: float) -> Dict[Tuple, float]:
        out: Dict[Tuple, float] = {}
        for (n, _), s in self._series.items():
            if n != name or s.kind != "counter":
                continue
            p = s.ring.points(window_s, now)
            if len(p) >= 2 and p[-1][0] > p[0][0]:
                r = (p[-1][1] - p[0][1]) / (p[-1][0] - p[0][0])
                out[s.origin] = out.get(s.origin, 0.0) + r
        return out

    def _origin_quantiles(self, name: str, window_s: float, now: float,
                          qs: Sequence[float]) -> Dict[Tuple, List]:
        bounds = (self._meta.get(name) or {}).get("bounds") or []
        acc: Dict[Tuple, List[float]] = {}
        for (n, _), s in self._series.items():
            if n != name or s.kind != "histogram":
                continue
            p = s.ring.points(window_s, now)
            if not p:
                continue
            first, last = p[0][1], p[-1][1]
            delta = [b - a for a, b in zip(first[0], last[0])] \
                if len(p) >= 2 else list(last[0])
            cur = acc.get(s.origin)
            acc[s.origin] = delta if cur is None else \
                [a + b for a, b in zip(cur, delta)]
        return {o: [bucket_quantile(bounds, c, q) for q in qs]
                for o, c in acc.items()}

    def fleet_summary(self, window_s: float = 30.0,
                      now: Optional[float] = None) -> Dict:
        """The ``ray-tpu top`` payload: one row per origin process with
        the fleet's key signals, plus fleet-level aggregates."""
        if now is None:
            import time
            now = time.time()
        with self._lock:
            origins = dict(self._origins)
            tok_rate = self._origin_rate(
                "serve_engine_tokens_total", window_s, now)
            tasks_rate = self._origin_rate(
                "runtime_tasks_finished_total", window_s, now)
            retx = self._origin_latest(
                "runtime_reliable_retransmits_total")
            stalls = self._origin_latest(
                "runtime_stream_credit_stall_seconds_total")
            qdepth = self._origin_latest("serve_engine_queue_depth")
            train_tps = self._origin_latest("train_tokens_per_s")
            mfu = self._origin_latest("train_mfu_pct")
            bubble = self._origin_latest("pipeline_bubble_fraction")
            mbx: Dict[Tuple, float] = {}
            for (n, _), s in self._series.items():
                if n != "pipeline_stage_mailbox_depth":
                    continue
                latest = s.ring.latest()
                if latest is not None:
                    mbx[s.origin] = max(mbx.get(s.origin, 0.0),
                                        latest[1])
            ttft = self._origin_quantiles(
                "serve_engine_ttft_seconds", window_s, now,
                (0.5, 0.99))
            reports_dropped = self._origin_latest(
                "runtime_metric_reports_dropped_total")
        rows = []
        for okey in sorted(origins):
            node, pid, role = okey
            q = ttft.get(okey, (None, None))
            rows.append({
                "node": node, "pid": pid, "role": role,
                "last_report_s": round(max(0.0, now -
                                           origins[okey]["ts"]), 1),
                "tokens_per_s": round(tok_rate.get(okey, 0.0), 1),
                "train_tokens_per_s": round(train_tps.get(okey, 0.0),
                                            1),
                "tasks_per_s": round(tasks_rate.get(okey, 0.0), 2),
                "queue_depth": qdepth.get(okey),
                "ttft_p50_ms": None if q[0] is None
                else round(q[0] * 1e3, 1),
                "ttft_p99_ms": None if q[1] is None
                else round(q[1] * 1e3, 1),
                "bubble": bubble.get(okey),
                "mfu_pct": mfu.get(okey),
                "mailbox_depth": mbx.get(okey),
                "retransmits": retx.get(okey, 0.0),
                "credit_stall_s": round(stalls.get(okey, 0.0), 2),
                "reports_dropped": reports_dropped.get(okey, 0.0),
            })
        fleet = {
            "processes": len(rows),
            "tokens_per_s": round(sum(r["tokens_per_s"]
                                      for r in rows), 1),
            "train_tokens_per_s": round(
                sum(r["train_tokens_per_s"] for r in rows), 1),
            "tasks_per_s": round(sum(r["tasks_per_s"] for r in rows),
                                 2),
            "retransmits": sum(r["retransmits"] for r in rows),
            "credit_stall_s": round(sum(r["credit_stall_s"]
                                        for r in rows), 2),
        }
        return {"window_s": window_s, "ts": now, "rows": rows,
                "fleet": fleet}


#: counter tracks /timeline renders by default (next to the spans)
DEFAULT_TIMELINE_COUNTERS = (
    "serve_engine_tokens_total", "serve_engine_queue_depth",
    "serve_engine_tokens_per_s", "train_tokens_per_s",
    "pipeline_stage_mailbox_depth", "pipeline_bubble_fraction",
    "runtime_scheduler_queued_tasks", "runtime_tasks_finished_total",
)
