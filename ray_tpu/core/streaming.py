"""Streaming generator tasks: the caller side of ``num_returns="streaming"``.

Equivalent of the reference's ``ObjectRefGenerator``
(``python/ray/_raylet.pyx`` ``ObjectRefGenerator`` / ``StreamingObjectRefGenerator``
backed by ``TaskManager::ObjectRefStream``, ``task_manager.h``): a worker
executing a generator (or async-generator) task eagerly stores each
yielded item as its own object — ``ObjectID.for_task_return(task_id, i)``
— and reports it with a ``STREAM_ITEM`` control message the moment it
exists; ``STREAM_EOF`` closes the stream. Both ride the reliable-delivery
layer (``core/reliable.py``), so item reports are exactly-once-effect and
the per-index bookkeeping here only has to absorb *reordering* (a
retransmitted item can land after younger ones) and *replay* (lineage
re-execution after a mid-stream worker death re-reports from index 1).

The owner-side :class:`StreamState` is the analog of the reference's
``ObjectRefStream``: it buffers minted item refs by index, hands them to
the consumer strictly in yield order, tracks EOF, and reports cumulative
consumption back to the producer (``STREAM_CREDIT``) so a fast producer
blocks at the backpressure window instead of flooding the object store
(reference: ``_generator_backpressure_num_objects``).

Reference counting is per item: every reported item registers one local
ref owned by the stream; ``__next__`` transfers that ref to the consumer,
so consumed items are freed independently of the stream and of each
other. ``close()`` (or GC of an abandoned generator) drops the buffered
refs and cancels the producer task, so early termination leaks neither
objects nor a running generator.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple, TYPE_CHECKING

from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef

if TYPE_CHECKING:  # pragma: no cover
    from ray_tpu.core.runtime import Runtime


class StreamState:
    """Owner-side record of one in-flight streaming task (reference:
    ``TaskManager::ObjectRefStream``). Created BEFORE the task is
    submitted so the earliest ``STREAM_ITEM`` cannot race it."""

    def __init__(self, runtime: "Runtime", task_id_b: bytes):
        self.runtime = runtime
        self.task_id_b = task_id_b
        self.cond = threading.Condition()
        #: minted-but-unconsumed item refs, keyed by 1-based yield index
        self.items: Dict[int, ObjectRef] = {}
        #: next index to hand to the consumer (== 1 + items consumed)
        self.next_index = 1
        #: total item count, set by STREAM_EOF (error item included)
        self.eof_index: Optional[int] = None
        #: stream-level failure (actor died, delivery gave up, task
        #: failed terminally with retries exhausted) — raised at next()
        self.error: Optional[BaseException] = None
        #: identity of the latest reporting worker (credits go here;
        #: a lineage replay moves it to the replaying worker)
        self.producer: Optional[bytes] = None
        self.closed = False
        #: highest index ever reported (replay/reorder dedup)
        self.received_max = 0
        #: causal trace of the producing task (core/events.py):
        #: ``(trace_id, parent_span)`` — rides STREAM_CREDIT so every
        #: control hop of the stream carries the link
        self.trace: Optional[tuple] = None
        #: credit batching: cumulative credits are idempotent, so the
        #: consumer only reports every ``credit_batch`` items — EXCEPT
        #: when its buffer just drained (the producer may be blocked at
        #: the window; an unsent credit there would deadlock). Halves of
        #: small windows flush eagerly; 1 restores per-item credits.
        self.credit_batch = max(
            1, min(8, getattr(runtime.config,
                              "generator_backpressure_num_objects",
                              64) // 2))
        self.last_credit = 0
        #: wait_any subscribers: Events set on every readiness edge
        #: (item buffered, EOF, failure, close)
        self._waiters: List[threading.Event] = []

    def _wake_waiters_locked(self) -> None:
        for ev in self._waiters:
            ev.set()

    def add_waiter(self, ev: threading.Event) -> None:
        with self.cond:
            self._waiters.append(ev)

    def remove_waiter(self, ev: threading.Event) -> None:
        with self.cond:
            try:
                self._waiters.remove(ev)
            except ValueError:
                pass

    # ------------------------------------------------------- report side
    def on_item(self, index: int, meta: dict, producer: Optional[bytes]
                ) -> None:
        """Pump-thread: one item report arrived. Seeds the owner's meta
        table (so a plain ``get`` on the ref resolves) and mints the
        stream-owned ref — exactly once per index, however many times a
        retransmit or lineage replay re-reports it."""
        rt = self.runtime
        b = meta["object_id"]
        drop_now = False
        with self.cond:
            if producer is not None:
                self.producer = producer
            already_consumed = index < self.next_index
            # "never minted" == not consumed and not buffered. This must
            # NOT be a high-water-mark test: a chaos-delayed item can
            # arrive AFTER its younger siblings, and treating it as a
            # duplicate would leave a permanent gap the consumer hangs on.
            first_sighting = not already_consumed \
                and index not in self.items
            self.received_max = max(self.received_max, index)
        if not first_sighting and not already_consumed:
            # buffered duplicate: meta already seeded, ref already minted
            return
        inline_local = rt._owner_local and meta.get("inline") is not None \
            and meta.get("error") is None
        oid = ObjectID(b)
        if first_sighting:
            rc = rt.reference_counter
            if inline_local:
                # owner-local item: no controller entry, no deltas —
                # suppression must precede the ref's +1 (mirror of put())
                rc.mark_untracked(oid)
            ref = ObjectRef(oid, rt.worker_id, _register=False)
            rc.add_local_reference(ref)
            ref._registered = True
        with rt._meta_lock:
            rt._meta[b] = meta
            if inline_local:
                rt._local_objects[b] = None
        from ray_tpu.core.runtime import _MetaReady
        rt.memory_store.put(oid, _MetaReady(meta), force=True)
        if not first_sighting:
            # replay of a consumed index (lineage re-execution): meta
            # refreshed. Re-send the cumulative credit to the NEW
            # producer — its window opens from zero, and the consumer
            # will never re-consume these indices, so without this a
            # replay with window <= consumed deadlocks at the window.
            with self.cond:
                consumed = self.next_index - 1
                producer = self.producer
                self.last_credit = consumed
            rt._stream_send_credit(self.task_id_b, consumed, producer,
                                   self.trace)
            return
        with self.cond:
            if self.closed:
                drop_now = True  # late item on a cancelled stream
            else:
                self.items[index] = ref
                self.cond.notify_all()
                self._wake_waiters_locked()
        if drop_now:
            # the +1/-1 pair nets to a 0-delta for tracked items, so the
            # controller still learns the object lived and died
            del ref

    def on_eof(self, count: int, producer: Optional[bytes]) -> None:
        with self.cond:
            if producer is not None:
                self.producer = producer
            # first EOF wins: a replayed generator cancelled early (or
            # a duplicate execution) must not shrink the stream
            if self.eof_index is None:
                self.eof_index = count
            self.cond.notify_all()
            self._wake_waiters_locked()

    def fail(self, err: BaseException) -> None:
        """Terminal task failure with no more replays coming: every
        blocked and future ``next()`` raises ``err``."""
        with self.cond:
            if self.error is None:
                self.error = err
            self.cond.notify_all()
            self._wake_waiters_locked()

    # ------------------------------------------------------ consumer side
    def _done_locked(self) -> bool:
        return self.eof_index is not None and self.next_index > self.eof_index

    def next_ref(self, timeout: Optional[float] = None) -> ObjectRef:
        """Block until the next in-order item is available and transfer
        its ref to the caller. Raises ``StopIteration`` at EOF, the
        stream error on terminal failure, ``GetTimeoutError`` on
        timeout."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self.cond:
            while True:
                if self.closed:
                    from ray_tpu.exceptions import StreamCancelledError
                    raise StreamCancelledError(TaskID(self.task_id_b))
                ref = self.items.pop(self.next_index, None)
                if ref is not None:
                    self.next_index += 1
                    consumed = self.next_index - 1
                    producer = self.producer
                    # batched credits: flush when the buffer drained
                    # (producer may be window-blocked) or every
                    # credit_batch items; skipped credits are covered
                    # by the next flush (cumulative).
                    send_credit = (not self.items) or \
                        consumed - self.last_credit >= self.credit_batch
                    if send_credit:
                        self.last_credit = consumed
                    break
                if self._done_locked():
                    # fully consumed: the runtime can forget the routing
                    # record (late lineage replays seed metas without it)
                    self.runtime._stream_finished(self.task_id_b)
                    raise StopIteration
                if self.error is not None:
                    raise self.error
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    from ray_tpu.exceptions import GetTimeoutError
                    raise GetTimeoutError(
                        f"no stream item within {timeout}s")
                self.cond.wait(0.2 if remaining is None
                               else min(0.2, remaining))
        if send_credit:
            self.runtime._stream_send_credit(self.task_id_b, consumed,
                                             producer, self.trace)
        return ref

    def next_ready(self, timeout: Optional[float] = None) -> bool:
        """Wait until the next item is ready (or the stream is done /
        failed) WITHOUT consuming it. Returns True when ``next_ref``
        would return immediately, False on timeout."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self.cond:
            while True:
                if self.closed or self.next_index in self.items \
                        or self._done_locked() or self.error is not None:
                    return True
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self.cond.wait(0.2 if remaining is None
                               else min(0.2, remaining))

    def close(self) -> list:
        """Mark closed and strip the buffered refs out (the runtime
        drops them and cancels the producer). Idempotent."""
        with self.cond:
            if self.closed:
                return []
            self.closed = True
            refs = list(self.items.values())
            self.items.clear()
            self.cond.notify_all()
            self._wake_waiters_locked()
            return refs

    def finished(self) -> bool:
        with self.cond:
            return self.closed or self.error is not None \
                or self._done_locked()


class ObjectRefGenerator:
    """Caller-facing handle of a streaming task (reference:
    ``ObjectRefGenerator``, python/ray/_raylet.pyx). Iterating yields
    ``ObjectRef``s in the producer's yield order; ``ray_tpu.get`` each
    to materialize (a mid-stream exception is delivered as the failing
    item — its ``get`` raises). Supports sync and async iteration,
    next-ready waiting, and early termination via ``close()``."""

    def __init__(self, state: StreamState):
        self._state = state

    # -------------------------------------------------------------- sync
    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        return self._state.next_ref()

    def next_ref(self, timeout: Optional[float] = None) -> ObjectRef:
        """``__next__`` with a timeout (``GetTimeoutError`` on expiry)."""
        return self._state.next_ref(timeout)

    def next_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the next item is available (or the stream has
        ended) without consuming it; False on timeout."""
        return self._state.next_ready(timeout)

    def ready_refs(self, max_items: Optional[int] = None) -> List[ObjectRef]:
        """Drain every already-buffered in-order item WITHOUT blocking
        (at most ``max_items``). A fan-in consumer woken by ``wait_any``
        uses this to take a producer's whole burst in one pass instead
        of one wakeup per item. Returns possibly-empty; EOF/failure are
        NOT consumed here — the next ``next_ref()`` surfaces them."""
        out: List[ObjectRef] = []
        st = self._state
        while max_items is None or len(out) < max_items:
            with st.cond:
                if st.closed or st.next_index not in st.items:
                    break
            try:
                out.append(st.next_ref(timeout=0))
            except Exception:
                break
        return out

    # ------------------------------------------------------------- async
    def __aiter__(self) -> "ObjectRefGenerator":
        return self

    async def __anext__(self) -> ObjectRef:
        import asyncio
        loop = asyncio.get_event_loop()
        sentinel = object()

        def pull():
            try:
                return self._state.next_ref()
            except StopIteration:
                # StopIteration must not cross the executor future (it
                # would be swallowed into a RuntimeError inside the
                # coroutine machinery)
                return sentinel

        out = await loop.run_in_executor(None, pull)
        if out is sentinel:
            raise StopAsyncIteration
        return out

    # ---------------------------------------------------------- control
    def task_id(self) -> TaskID:
        return TaskID(self._state.task_id_b)

    def is_finished(self) -> bool:
        """True when the stream can yield nothing further (EOF reached
        and consumed, terminally failed, or cancelled)."""
        return self._state.finished()

    def close(self) -> None:
        """Early termination: cancel the producer task and drop every
        buffered (unconsumed) item ref. Safe to call repeatedly."""
        self._state.runtime._close_stream(self._state)

    cancel = close

    def __del__(self):
        try:
            if not self._state.finished():
                self.close()
        except Exception:
            pass

    def __reduce__(self):
        raise TypeError(
            "ObjectRefGenerator is not serializable: it is owned by the "
            "submitting process (pass the consumed values, or the item "
            "ObjectRefs, instead)")

    def __repr__(self):
        return f"ObjectRefGenerator({TaskID(self._state.task_id_b).hex()[:16]})"


def wait_any(generators: Sequence[ObjectRefGenerator],
             timeout: Optional[float] = None, num_returns: int = 1
             ) -> Tuple[List[ObjectRefGenerator],
                        List[ObjectRefGenerator]]:
    """Block until at least ``num_returns`` of ``generators`` are
    *actionable* — their next ``next_ref()`` would return (an in-order
    item is buffered) or terminate immediately (EOF fully consumed,
    terminal failure, cancelled). Returns ``(ready, not_ready)`` in the
    input order, like ``ray_tpu.wait`` for plain refs; on timeout the
    ready list may be shorter than ``num_returns`` (possibly empty).

    Event-driven, not polling: every stream wakes a shared Event on its
    readiness edges (item report, EOF, failure, close), so a fan-in
    consumer — e.g. the MPMD 1F1B scheduler draining one stream per
    pipeline stage — reacts at delivery latency regardless of how many
    streams it watches.
    """
    gens = list(generators)
    if not gens:
        return [], []
    num_returns = max(1, min(num_returns, len(gens)))
    import time as _time
    deadline = None if timeout is None else _time.monotonic() + timeout
    ev = threading.Event()
    for g in gens:
        g._state.add_waiter(ev)
    try:
        while True:
            ready = [g for g in gens if g._state.next_ready(timeout=0)]
            if len(ready) >= num_returns:
                break
            remaining = None if deadline is None \
                else deadline - _time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            ev.wait(0.2 if remaining is None else min(0.2, remaining))
            ev.clear()
    finally:
        for g in gens:
            g._state.remove_waiter(ev)
    ready_ids = {id(g) for g in ready}
    return ready, [g for g in gens if id(g) not in ready_ids]
