"""Control-plane wire protocol over ZeroMQ.

Equivalent role to the reference's gRPC layer (``src/ray/rpc/``) plus the
protobuf schema (``src/ray/protobuf/``). Transport: one ROUTER socket bound
by the controller at ``ipc://<session>/controller.sock``; every other
process (driver, node managers, workers) connects a DEALER whose identity is
its binary WorkerID/NodeID. Messages are two frames: ``[type][payload]``
with the payload pickled (protocol 5). Request/response pairs carry a
correlation id; one-way notifications don't.

ZeroMQ gives the same properties the reference builds on asio+gRPC: ordered
per-peer delivery, async send queues, and broker routing by identity.
"""

from __future__ import annotations

import itertools
import os
import pickle
import struct
import threading
import time
from typing import Any, Dict, Optional

# ---- message types ----
# registration / lifecycle
REGISTER = b"REG"            # {kind, id, node_id, pid} -> {ok, session_info}
REGISTER_REPLY = b"REGR"
SHUTDOWN = b"BYE"
# tasks
SUBMIT_TASK = b"SUB"         # {spec}
SUBMIT_BATCH = b"SBB"        # {specs: [spec, ...]} — pipelined submission
TASK_ASSIGN = b"ASG"         # controller->node {spec}
TASK_DISPATCH = b"DSP"       # node->worker {spec}
TASK_DONE = b"DON"           # worker->controller {task_id, results, error}
TASK_RESULT = b"RES"         # controller->owner {object_id, inline|location|error}
CANCEL_TASK = b"CAN"
# actors
CREATE_ACTOR = b"CAC"
ACTOR_UPDATE = b"AUP"        # controller->subscribers {actor_id, state, ...}
SUBMIT_ACTOR_TASK = b"SAT"
KILL_ACTOR = b"KIL"
GET_ACTOR = b"GAC"           # lookup by name
ACTOR_ADDR = b"AAD"          # caller->controller {actor_id} -> {worker}|{dead}
                             # (long-poll: held until the actor is ALIVE)
ACTOR_CALL = b"ACL"          # caller->actor worker DIRECT {spec} or
                             # compact {tmpl, caller, task_id, seq, ...}
TMPL_MISS = b"TMS"           # worker->caller DIRECT {task_id, tmpl}:
                             # resend the call with its full spec (the
                             # template was evicted or its registration
                             # message was lost)
CANCEL_QUEUED = b"CQD"       # ->worker direct {task_id, force}
# blocked-worker protocol (reference: NotifyDirectCallTaskBlocked /
# NotifyUnblocked — a worker blocked in ray.get releases its cpu and
# returns its unstarted pipeline so the cluster can make progress)
NOTIFY_BLOCKED = b"NBK"      # worker->controller {task_id}
NOTIFY_UNBLOCKED = b"NUB"    # worker->controller {}
TASK_HANDBACK = b"HBK"       # worker->controller {specs: [...]}
# streaming generator tasks (reference: num_returns="streaming" +
# ReportGeneratorItemReturns, task_manager.cc — each yielded item is its
# own object, eagerly reported to the owner while the task still runs)
STREAM_ITEM = b"SIT"         # worker->owner DIRECT {task_id, index, meta,
                             # worker}: one yielded item's result meta
STREAM_EOF = b"SEF"          # worker->owner DIRECT {task_id, count,
                             # worker, error?}: the stream is complete
STREAM_CREDIT = b"SCR"       # owner->worker DIRECT {task_id, consumed}:
                             # cumulative consumer progress — opens the
                             # producer's backpressure window
# objects
PUT_OBJECT = b"PUT"          # seal notification {object_id, node_id, size, owner}
FREE_OBJECT = b"FRE"         # controller->node {object_id}
GET_LOCATION = b"LOC"        # {object_id} -> {node_id|None, inline|None}
FETCH_OBJECT = b"FOB"        # controller->owner {object_id}: publish this
                             # owner-local object's value (PUT_OBJECT) so a
                             # parked borrower/dep can resolve
PULL_OBJECT = b"PUL"         # controller->dest node: pull this object
PULL_REQUEST = b"PRQ"        # dest->src node DIRECT: stream it to me
PUSH_OBJECT = b"PSH"         # src->dest node DIRECT: chunked payload
PULL_FAILED = b"PLF"         # src->dest direct / dest->controller: pull failed
STORE_RPC = b"SRP"           # worker->node DIRECT {op, rid, ...}:
                             # make_room {bytes} -> {freed} |
                             # restore {object_id} -> {ok} — plasma's
                             # create-queue + restore requests analog
LOCATE_OBJECT = b"LOB"       # controller->node {object_id}: if your store
                             # holds it, announce it (repairs a directory
                             # hole left by a producer killed mid-report)
CHUNK_ACK = b"CAK"           # dest->src DIRECT: chunk received (flow control)
RECONNECT = b"RCN"           # controller->peer: re-register + re-announce
                             # (sent after a controller restart)
REF_DELTAS = b"RFD"          # {deltas: {bytes: int}}
# direct normal-task transport (reference: worker leases,
# direct_task_transport.h — the owner leases workers and pushes tasks
# peer-to-peer; the controller only grants/reclaims leases)
LEASE_WORKERS = b"LSW"       # driver->controller {count, rid} -> {workers}
RELEASE_LEASES = b"RLW"      # driver->controller {workers: [identity]}
LEASE_REVOKED = b"LRV"       # controller->driver {worker}: leased worker
                             # died — resubmit its in-flight tasks
LEASE_GRANT = b"LGR"         # controller->driver {workers}: deferred
                             # grant for a parked LEASE_WORKERS request
OWNER_FREE = b"OFR"          # owner->controller {object_ids: [bytes]}:
                             # owner already evicted these never-shared
                             # extents; drop metadata + node bookkeeping
# kv / functions
KV_OP = b"KVO"               # {op: put|get|del|keys|exists, ns, key, value}
EXPORT_FUNCTION = b"EXF"     # {key, blob}
FETCH_FUNCTION = b"FEF"      # {key} -> {blob}
# placement groups
CREATE_PG = b"CPG"
REMOVE_PG = b"RPG"
PG_UPDATE = b"PGU"
# cluster
HEARTBEAT = b"HBT"           # node->controller {node_id, available, total, stats}
WORKER_PINNED = b"WPN"       # controller->node {worker_identity}: hosts an actor
PING = b"PNG"                # driver->controller liveness poke: lets a
                             # restarted controller ask it to RECONNECT
NODE_UPDATE = b"NUP"
WORKER_EXIT = b"WEX"
STATE_QUERY = b"STQ"         # {what, filters} -> rows
PROFILE_SELF = b"PRF"        # controller->worker {rid, duration_s}:
                             # sample your own stacks (dashboard
                             # profiling; reference: reporter agent's
                             # py-spy endpoints)
PROFILE_RESULT = b"PRR"      # worker->controller {rid, collapsed, ...}
TIMELINE_EVENTS = b"TLE"     # worker->controller span/timeline batch
TASK_EVENTS = b"TEV"         # any->controller {events: [...]}: flight-
                             # recorder flush (core/events.py). Rides
                             # the reliable layer (exactly-once-effect)
                             # but is fire-and-forget for the producer —
                             # a flush never blocks task progress.
METRIC_REPORT = b"MRT"       # any->controller {origin, seq, ts,
                             # metrics}: periodic full metric snapshot
                             # (util/metrics.py::MetricsReporter) for
                             # the fleet metrics plane
                             # (core/metrics_plane.py). Reliable like
                             # TEV, fire-and-forget for the producer;
                             # stale in-flight reports are superseded
                             # (drop-oldest, counted).
REQUEST_SPANS = b"RSP"       # any->controller {request_id, part, seq,
                             # spans: [...]}: per-request trace span
                             # batch (serve/request_trace.py). Reliable
                             # like TEV, fire-and-forget for the
                             # producer; tail-sampled at the source so
                             # only slow/failed/1-in-N requests ship.
PUBSUB = b"PUB"              # {channel, data} fanout
SUBSCRIBE = b"SSC"           # {channel}
GENERIC_REPLY = b"RPL"
ERROR_REPLY = b"ERR"
MSG_BATCH = b"MBB"           # {msgs: [(mtype, payload), ...]} — wire batching
MSG_ACK = b"ACK"             # {acks: [(sender_tag, [(lo, hi), ...])]}:
                             # batched ack ranges for reliably-delivered
                             # one-way messages (core/reliable.py). Never
                             # itself tracked — a lost ack just costs one
                             # deduped retransmit.

_DUMPS_PROTO = 5


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=_DUMPS_PROTO)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def wire_sizeof(obj: Any) -> int:
    """Wire footprint of ``obj`` as the runtime would actually ship it:
    pickle-5 meta plus the out-of-band buffers the zero-copy serializer
    strips (``core/serialization.py``). Large numpy/jax payloads are
    counted at their raw buffer size instead of being copied through a
    flat pickle — this is the accounting the disagg KV hand-off reports
    as ``serve_kv_ship_bytes_total``."""
    try:
        from ray_tpu.core.serialization import default_context
        return int(default_context().serialize(obj).total_bytes())
    except Exception:
        return len(dumps(obj))


class ReplyWaiter:
    """Correlates request/reply over the async socket pump.

    Two modes per request: blocking (``new_request()`` + ``wait()``) and
    callback (``new_request(callback=...)``) — the callback runs on the
    pump thread when the reply lands, so it must not block (reference:
    the ClientCallManager completion-queue callbacks, rpc/client_call.h).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._events: Dict[bytes, threading.Event] = {}
        self._replies: Dict[bytes, Any] = {}
        self._callbacks: Dict[bytes, Any] = {}

    _rid_counter = itertools.count(1)

    def new_request(self, callback=None) -> bytes:
        # rids only need per-process uniqueness (replies are routed by peer
        # identity); a counter avoids a urandom syscall per RPC
        rid = struct.pack("<QQ", os.getpid(), next(self._rid_counter))
        with self._lock:
            if callback is not None:
                self._callbacks[rid] = callback
            else:
                self._events[rid] = threading.Event()
        return rid

    def fulfill(self, rid: bytes, reply: Any) -> bool:
        with self._lock:
            cb = self._callbacks.pop(rid, None)
            if cb is None:
                ev = self._events.get(rid)
                if ev is None:
                    return False
                self._replies[rid] = reply
        if cb is not None:
            cb(reply)
            return True
        ev.set()
        return True

    def wait(self, rid: bytes, timeout: Optional[float],
             mtype: Optional[bytes] = None) -> Any:
        started = time.monotonic()
        with self._lock:
            ev = self._events[rid]
        if not ev.wait(timeout):
            with self._lock:
                self._events.pop(rid, None)
            from ray_tpu.exceptions import RpcTimeoutError
            raise RpcTimeoutError(mtype, time.monotonic() - started)
        with self._lock:
            self._events.pop(rid, None)
            return self._replies.pop(rid)


def socket_path(session_dir: str) -> str:
    return f"ipc://{session_dir}/controller.sock"
