"""Per-process runtime: the core-worker library.

Equivalent of the reference's ``CoreWorker`` (``src/ray/core_worker/
core_worker.h``; Cython surface ``python/ray/_raylet.pyx:3177``): lives in
every driver and worker process; provides submit_task / create_actor /
submit_actor_task / get / put / wait / cancel, owns the in-process memory
store, the reference counter, and the serialization context. A background
pump thread owns the DEALER socket (all control traffic); synchronous RPCs
are correlated via ReplyWaiter.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

import collections
from collections import OrderedDict
from queue import Empty, SimpleQueue

import zmq

from ray_tpu.core import chaos as CH
from ray_tpu.core import direct as D
from ray_tpu.core import events as EV
from ray_tpu.core import protocol as P
from ray_tpu.core import reliable as RD
from ray_tpu.core.config import Config, get_config
from ray_tpu.core.ids import (
    ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID)
from ray_tpu.core.memory_store import InProcessStore
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.reference_counter import ReferenceCounter
from ray_tpu.core.serialization import SerializationContext, SerializedObject
from ray_tpu.exceptions import ObjectStoreFullError
from ray_tpu.core.shm_store import make_client
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.exceptions import GetTimeoutError

logger = logging.getLogger(__name__)


#: flusher-queue target marker for deferrable controller messages
_DEFER = object()


class _ArgPlaceholder:
    """Marks a positional arg that was a top-level ObjectRef."""
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (_ArgPlaceholder, (self.index,))


class Runtime:
    def __init__(self, kind: str, session_dir: str, node_id: NodeID,
                 worker_id: Optional[WorkerID] = None,
                 shm_session: Optional[str] = None):
        self.kind = kind
        self.session_dir = session_dir
        self.node_id = node_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.job_id = JobID.from_int(0)
        self.config: Config = get_config()

        # flight recorder (core/events.py): bounded per-process event
        # ring, flushed to the controller as TASK_EVENTS. Created
        # before the reliable layer so transport events are captured
        # from the first message.
        self.recorder = EV.make_recorder(
            f"{kind}:{self.worker_id.hex()[:12]}", self.config,
            send=self._send_events)

        # seeded fault injection (chaos.py): None in production — every
        # hook below is a single attribute check when disabled
        self._chaos = CH.maybe_injector(kind,
                                        self_id=self.worker_id.binary())
        self._chaos_dedup = CH.SeqDeduper() if self._chaos is not None \
            else None
        # lease/reconnect retry backoff: exponential with full jitter
        # (replaces the old fixed 2.0s sleeps — under chaos every driver
        # retrying in lockstep hammered the restarted controller)
        from ray_tpu.util.backoff import ExponentialBackoff
        _bo_rng = self._chaos.rng_for("lease-backoff") \
            if self._chaos is not None else None
        self._lease_backoff = ExponentialBackoff(
            base=self.config.lease_backoff_base_s,
            cap=self.config.lease_backoff_cap_s, rng=_bo_rng)
        self._topup_backoff = ExponentialBackoff(
            base=self.config.lease_backoff_base_s,
            cap=self.config.lease_backoff_cap_s, rng=_bo_rng)
        # reliable-delivery sublayer (core/reliable.py): critical one-way
        # messages get ack/retransmit; retransmit duplicates are deduped
        # receiver-side. Resends re-enter the flusher queue (thread-safe)
        # and pass the chaos filter again like any first transmission.
        self._reliable = RD.maybe_transport(
            self.config, self._reliable_resend, self._reliable_ack,
            rng=self._chaos.rng_for("retransmit")
            if self._chaos is not None else None, name=kind,
            recorder=self.recorder)

        # fleet metrics reporter (util/metrics.py): periodic full-
        # registry snapshots to the controller's metrics plane as
        # METRIC_REPORT — fire-and-forget like the flight recorder,
        # with superseded in-flight reports abandoned from the
        # reliable ring (drop-oldest, counted) so a dead link never
        # grows a backlog.
        from ray_tpu.util import metrics as MX
        self.metrics_reporter = MX.make_reporter(
            self._send_metric_report,
            {"node": node_id.hex()[:12], "pid": os.getpid(),
             "role": kind},
            self.config,
            pending_drop=(
                (lambda keep: self._reliable.drop_oldest_of(
                    P.METRIC_REPORT, keep))
                if self._reliable is not None else None))

        self.memory_store = InProcessStore()
        self.reference_counter = ReferenceCounter(self._flush_ref_deltas)
        self.reference_counter.set_owner_zero_fn(self._on_owner_zero)
        self.serialization = SerializationContext(self)
        self.shm = make_client(shm_session) if shm_session else None
        self.shm_session = shm_session

        # Eager owner-side recycling (reference: owner-based GC frees an
        # object the moment its owner's counts hit zero). put() objects
        # whose refs never leave this process are evicted directly from
        # the shared segment on last-ref-drop — the extent returns to the
        # allocator freelist with its pages still resident, so a hot
        # put loop recycles warm extents instead of faulting fresh ones.
        self._eager_owned: Dict[bytes, None] = {}
        self._escaped_refs: "OrderedDict[bytes, None]" = OrderedDict()
        self._eager_lock = threading.Lock()
        self._empty_args_blob: Optional[bytes] = None

        # Owner-local small objects (reference: the in-process store +
        # owner-based object directory — the GCS never hears about
        # small objects). Inline puts and task returns stay out of the
        # controller's directory/refcount tables until a ref ESCAPES
        # (pickled or passed as a task arg), at which point the object
        # is promoted and its value published. Guarded by _meta_lock.
        self._owner_local = bool(
            getattr(self.config, "owner_local_objects", False))
        #: owner-local oids whose meta/value live only in this process
        self._local_objects: Dict[bytes, None] = {}
        #: oids to publish to the controller the moment their result
        #: arrives (escaped-while-pending, or a borrower FETCH_OBJECT)
        self._publish_on_result: Dict[bytes, None] = {}

        # Direct normal-task transport (reference: worker leases,
        # direct_task_transport.h): the driver leases workers from the
        # controller and pushes dependency-free default-shape tasks to
        # them peer-to-peer; only TASK_DONE accounting reaches the
        # controller. State guarded by _lease_lock.
        self._lease_lock = threading.Lock()
        self._lease_pool: List[bytes] = []
        self._lease_inflight: Dict[bytes, int] = {}
        self._lease_state = "none"      # none | pending | ready
        self._lease_backoff_until = 0.0
        self._direct_tids: Dict[bytes, bytes] = {}  # tid -> worker
        # saturated-lease overflow queues HERE and drains on completions
        # (falling back to the controller would starve its queue behind
        # lease-held CPUs and trigger reclaim thrash)
        self._direct_backlog: Deque[TaskSpec] = collections.deque()
        #: memory bound on locally-queued direct tasks — NOT a
        #: throughput valve (the controller path is slower per task).
        #: Both a count cap and a byte cap: specs carry the full inline
        #: args blob, so count alone bounds nothing when tasks pass
        #: megabyte args by value.
        self._direct_backlog_cap = int(os.environ.get(
            "RAY_TPU_DIRECT_BACKLOG_CAP", "200000"))
        self._direct_backlog_bytes_cap = int(os.environ.get(
            "RAY_TPU_DIRECT_BACKLOG_BYTES_CAP", str(1 << 31)))  # 2 GiB
        self._direct_backlog_bytes = 0
        #: a LEASE_WORKERS request is outstanding (initial or top-up)
        self._lease_req_inflight = False
        #: after an empty top-up grant (cluster fully leased — usually by
        #: us), don't re-ask until this deadline: each empty round trip
        #: costs a controller hop and grants nothing
        self._lease_topup_backoff = 0.0

        # object_id(bytes) -> result meta {"inline"|"node_id"/"size"|"error"}
        self._meta: Dict[bytes, dict] = {}
        self._meta_lock = threading.Lock()
        #: streaming generator tasks we own (task_id bytes -> StreamState);
        #: entries are routing state only — dropped at close / terminal
        #: failure / full consumption (core/streaming.py)
        self._streams: Dict[bytes, Any] = {}
        self._streams_lock = threading.Lock()
        #: worker-side hook (WorkerExecutor): STREAM_CREDIT consumption
        #: reports for generator tasks executing in this process
        self.stream_credit_handler: Optional[Callable[[dict], None]] = None
        self._completion_cbs: Dict[bytes, List[Callable]] = {}
        self._pending_locations: Dict[bytes, float] = {}  # object -> probe ts

        self.replies = P.ReplyWaiter()
        self._put_counter = 0
        self._task_counter = 0
        self._lock = threading.Lock()
        self._driver_task_id = TaskID.for_driver(self.job_id)
        # task context is thread-local: concurrent actor tasks must not
        # attribute puts/events to each other's task ids
        self._task_ctx = threading.local()
        self._current_actor_id: Optional[ActorID] = None

        self.dispatch_handler: Optional[Callable[[dict], None]] = None
        #: WorkerExecutor hook: True while a task is queued/running (a
        #: reconnecting busy worker must not rejoin the idle pool)
        self.busy_probe: Optional[Callable[[], bool]] = None
        self._reconnect_gen: Optional[bytes] = None
        #: Installed by WorkerExecutor: called when the executing thread is
        #: about to block on a remote result / when it resumes (reference:
        #: CoreWorker NotifyDirectCallTaskBlocked, core_worker.cc)
        self.block_notifier = None
        self._early_dispatches: List[dict] = []
        self.pubsub_handlers: Dict[str, List[Callable]] = {}
        self.pg_events: Dict[bytes, dict] = {}
        self.pg_cond = threading.Condition()
        self._register_reply: Optional[dict] = None
        self._register_ev = threading.Event()
        self._stopped = threading.Event()
        self._timeline_buf: List[dict] = []

        # completion callbacks must not run on the pump thread (they may
        # materialize via blocking RPCs the pump itself fulfills)
        self._cb_queue: "SimpleQueue[Optional[Callable]]" = SimpleQueue()
        self._cb_thread = threading.Thread(
            target=self._cb_loop, name=f"{kind}-callbacks", daemon=True)
        self._cb_thread.start()

        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.DEALER)
        self.sock.setsockopt(zmq.IDENTITY, self.worker_id.binary())
        self.sock.setsockopt(zmq.LINGER, 0)
        # unbounded queues: a burst of task results must never be dropped
        # at the HWM (the control plane has no retransmit)
        self.sock.setsockopt(zmq.SNDHWM, 0)
        self.sock.setsockopt(zmq.RCVHWM, 0)
        self.sock.connect(P.socket_path(session_dir))
        self._send_lock = threading.Lock()
        # direct peer channel (reference: direct_actor_transport.h — actor
        # calls and task results move worker<->worker without the broker).
        # The ROUTER is recv-only (pump thread); outgoing peer DEALERs are
        # owned by the flusher thread.
        D.ensure_dir(session_dir)
        self.direct_sock = self.ctx.socket(zmq.ROUTER)
        self.direct_sock.setsockopt(zmq.LINGER, 0)
        self.direct_sock.setsockopt(zmq.SNDHWM, 0)
        self.direct_sock.setsockopt(zmq.RCVHWM, 0)
        self.direct_sock.bind(D.direct_addr(session_dir, self.worker_id.binary()))
        self._peer_socks: Dict[bytes, list] = {}  # flusher-owned: [sock, last_used]
        self._last_peer_prune = time.time()
        # client-side actor submitter state machine (reference:
        # CoreWorkerDirectActorTaskSubmitter: per-actor connection state +
        # pending queue, direct_actor_task_submitter.h)
        self._actors: Dict[bytes, dict] = {}
        self._actors_lock = threading.Lock()
        # normal-task specs we own that have not completed (resubmitted to
        # a restarted controller on RECONNECT)
        self._inflight_specs: Dict[bytes, TaskSpec] = {}
        self._inflight_lock = threading.Lock()
        # all sends go through one flusher thread: preserves FIFO order,
        # moves pickling off the caller's critical path, and coalesces
        # consecutive task submissions into SUBMIT_BATCH messages
        # (reference: pipelined submission, direct_task_transport.h:157)
        self._out_q: "SimpleQueue[Optional[Tuple[bytes, Any]]]" = SimpleQueue()
        self._flusher = threading.Thread(target=self._flush_loop,
                                         name=f"{kind}-flush", daemon=True)
        self._flusher.start()
        # wake channel so shutdown can interrupt the pump's long poll and
        # join it before closing the DEALER (zmq sockets are not
        # thread-safe; close must not race poll/recv)
        self._pump_wake_recv = self.ctx.socket(zmq.PULL)
        self._pump_wake_recv.bind(f"inproc://pump-wake-{id(self)}")
        self._pump_wake_send = self.ctx.socket(zmq.PUSH)
        self._pump_wake_send.connect(f"inproc://pump-wake-{id(self)}")
        self._pump = threading.Thread(target=self._pump_loop,
                                      name=f"{kind}-pump", daemon=True)
        self._pump.start()
        if kind == "driver":
            # liveness poke: an idle driver otherwise never speaks, so a
            # restarted controller could never ask it to RECONNECT (and
            # its in-flight submissions would hang forever)
            threading.Thread(target=self._ping_loop, name="driver-ping",
                             daemon=True).start()

    def _ping_loop(self) -> None:
        while not self._stopped.wait(2.0):
            self._send(P.PING, {})
            # GC latency bound: pending ref deltas below the batch
            # threshold still reach the controller within one period
            try:
                self.reference_counter.flush()
            except Exception:
                pass
            self.recorder.maybe_flush()
            self.metrics_reporter.maybe_report()

    @property
    def current_task_id(self) -> TaskID:
        return getattr(self._task_ctx, "task_id", self._driver_task_id)

    @current_task_id.setter
    def current_task_id(self, value: TaskID) -> None:
        self._task_ctx.task_id = value

    def _cb_loop(self) -> None:
        while True:
            fn = self._cb_queue.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                logger.exception("completion callback failed")

    # ------------------------------------------------------------ transport
    def _reliable_resend(self, target, mtype: bytes, payload) -> None:
        """Retransmit hook (reliable-layer thread): re-enqueue through
        the flusher so the resend takes the same path — stamped payloads
        pass through ``stamp()`` untouched."""
        if not self._stopped.is_set():
            self._out_q.put((target, mtype, payload))

    def _reliable_ack(self, route, payload) -> None:
        """Batched-ack hook: ship back over the link the stamped
        messages arrived on (None = the controller DEALER)."""
        if not self._stopped.is_set():
            self._out_q.put((route, P.MSG_ACK, payload))

    def _send(self, mtype: bytes, payload: Any) -> None:
        self._out_q.put((None, mtype, payload))

    def _send_events(self, evs: List[dict]) -> None:
        """Flight-recorder flush hook: fire-and-forget enqueue (the
        reliable layer gives the batch exactly-once-effect at the
        controller; the recorder's bounded ring means a dead link can
        never grow memory or block a task)."""
        if not self._stopped.is_set():
            self._send(P.TASK_EVENTS, {"events": evs})

    def _send_metric_report(self, payload: dict) -> None:
        """Metrics-reporter ship hook (same contract as
        :meth:`_send_events`)."""
        if not self._stopped.is_set():
            self._send(P.METRIC_REPORT, payload)

    def _send_direct(self, target: bytes, mtype: bytes, payload: Any) -> None:
        """Queue a message for a peer's direct channel (``target`` is the
        peer's identity bytes). Same-process sends short-circuit."""
        if target == self.worker_id.binary():
            try:
                self._on_message(mtype, payload)
            except Exception:
                logger.exception("%s: error in local direct %s", self.kind, mtype)
            return
        self._out_q.put((target, mtype, payload))

    def _send_many(self, msgs: List[Tuple[Optional[bytes], bytes, Any]]
                   ) -> None:
        """Enqueue several (target, mtype, payload) messages with ONE
        queue handoff — each put can cost a flusher-thread wakeup.
        Same-process targets still short-circuit."""
        rest = []
        me = self.worker_id.binary()
        for target, mtype, payload in msgs:
            if target == me:
                try:
                    self._on_message(mtype, payload)
                except Exception:
                    logger.exception("%s: error in local direct %s",
                                     self.kind, mtype)
            else:
                rest.append((target, mtype, payload))
        if rest:
            self._out_q.put(rest)

    def _sock_send(self, mtype: bytes, blob: bytes) -> None:
        with self._send_lock:
            self.sock.send_multipart([mtype, blob])

    def _peer_sock(self, target: bytes) -> "zmq.Socket":
        """Flusher-thread-only: lazily connected DEALER to a peer ROUTER."""
        ent = self._peer_socks.get(target)
        if ent is None:
            s = self.ctx.socket(zmq.DEALER)
            s.setsockopt(zmq.IDENTITY, self.worker_id.binary())
            s.setsockopt(zmq.LINGER, 0)
            s.setsockopt(zmq.SNDHWM, 0)
            s.connect(D.direct_addr(self.session_dir, target))
            ent = self._peer_socks[target] = [s, time.time()]
        else:
            ent[1] = time.time()
        return ent[0]

    def _prune_peer_socks(self, idle_s: float = 120.0) -> None:
        """Flusher-thread-only. ipc connects never fail, so a DEALER to a
        dead peer would otherwise queue messages forever (SNDHWM=0) and the
        socket itself leak; idle-pruning bounds both."""
        now = time.time()
        for target in [t for t, (_, used) in self._peer_socks.items()
                       if now - used > idle_s]:
            sock, _ = self._peer_socks.pop(target)
            try:
                sock.close(0)
            except Exception:
                pass

    def _send_deferred(self, mtype: bytes, payload: Any) -> None:
        """Queue a controller-bound message that tolerates a few ms of
        delay (TASK_DONE accounting for direct tasks — the owner already
        has the result; the controller only records). The flusher holds
        these up to ~3ms / 64 messages and ships ONE batch, so a sync
        call loop costs the controller one handler pass per batch
        instead of one per call."""
        self._out_q.put((_DEFER, mtype, payload))

    def _flush_loop(self) -> None:
        deferred: List[Tuple[bytes, Any]] = []
        deferred_at = 0.0
        while True:
            try:
                if deferred:
                    # bounded hold: wake in time to honor the 3ms window
                    wait = max(0.0,
                               deferred_at + 0.003 - time.monotonic())
                    try:
                        item = self._out_q.get(timeout=wait)
                    except Empty:
                        self._flush_box(None, deferred)
                        deferred = []
                        continue
                else:
                    item = self._out_q.get()
            except Exception:
                return
            batch = [item]
            while len(batch) < 512:
                try:
                    batch.append(self._out_q.get_nowait())
                except Empty:
                    break
            stop = False
            # per-target ordered message lists; None = controller
            boxes: Dict[Optional[bytes], List[Tuple[bytes, Any]]] = {}
            specs: List = []

            def close_specs() -> None:
                box = boxes.setdefault(None, [])
                if len(specs) == 1:
                    box.append((P.SUBMIT_TASK, {"spec": specs[0]}))
                elif specs:
                    box.append((P.SUBMIT_BATCH, {"specs": list(specs)}))
                specs.clear()

            for it in batch:
                if it is None:
                    stop = True
                    break
                # a list item is a multi-message put (_send_many)
                for target, mtype, payload in (
                        it if isinstance(it, list) else (it,)):
                    if target is _DEFER:
                        if not deferred:
                            deferred_at = time.monotonic()
                        deferred.append((mtype, payload))
                        continue
                    if target is None and mtype == P.SUBMIT_TASK:
                        specs.append(payload["spec"])
                        continue
                    if target is None:
                        close_specs()
                    boxes.setdefault(target, []).append((mtype, payload))
            close_specs()
            if deferred and (stop or len(deferred) >= 64
                             or boxes.get(None)):
                # ship alongside a controller-bound flush (free ride on
                # the same MSG_BATCH), at the size cap, or at shutdown
                boxes.setdefault(None, []).extend(deferred)
                deferred = []
            for target, msgs in boxes.items():
                self._flush_box(target, msgs)
            if time.time() - self._last_peer_prune > 30.0:
                self._last_peer_prune = time.time()
                self._prune_peer_socks()
            if stop:
                return

    def _flush_box(self, target: Optional[bytes],
                   msgs: List[Tuple[bytes, Any]]) -> None:
        if not msgs:
            return
        # getattr: unit tests drive _flush_box on bare fakes
        rel = getattr(self, "_reliable", None)
        if rel is not None:
            # stamp + ring-record critical one-way messages BEFORE the
            # chaos filter: a dropped message must already be tracked
            msgs = [(mt, rel.stamp(target, mt, pl)) for mt, pl in msgs]
        if getattr(self, "_chaos", None) is not None:
            msgs = self._chaos_filter(target, msgs)
            if not msgs:
                return
        send = self._sock_send if target is None else \
            (lambda mt, blob: self._peer_sock(target).send_multipart([mt, blob]))
        try:
            if len(msgs) == 1:
                send(msgs[0][0], P.dumps(msgs[0][1]))
            else:
                send(P.MSG_BATCH, P.dumps({"msgs": msgs}))
        except Exception:
            # one bad payload must not discard the whole batch: retry
            # each message individually, dropping only the culprit
            for mtype, payload in msgs:
                try:
                    send(mtype, P.dumps(payload))
                except Exception:
                    if not self._stopped.is_set():
                        logger.exception(
                            "%s: dropping unsendable %s", self.kind, mtype)

    def _chaos_filter(self, target: Optional[bytes],
                      msgs: List[Tuple[bytes, Any]]
                      ) -> List[Tuple[bytes, Any]]:
        """Fault-injection choke point for every outgoing message (the
        flusher thread owns all sends, so one hook covers the controller
        DEALER and every peer channel). Dropped messages vanish here;
        delayed ones re-enter the flusher queue on a timer; duplicates
        ship twice with one wire seq (receivers dedup)."""
        out: List[Tuple[bytes, Any]] = []
        for mtype, payload in msgs:
            for delay_s, pl in self._chaos.plan_send(target, mtype, payload):
                if delay_s > 0.0:
                    t = threading.Timer(delay_s, self._out_q.put,
                                        args=((target, mtype, pl),))
                    t.daemon = True
                    t.start()
                else:
                    out.append((mtype, pl))
        return out

    def request(self, mtype: bytes, payload: dict,
                timeout: Optional[float] = None) -> dict:
        rid = self.replies.new_request()
        payload = dict(payload, rid=rid)
        self._send(mtype, payload)
        reply = self.replies.wait(rid, timeout or self.config.rpc_timeout_s,
                                  mtype=mtype)
        if isinstance(reply, dict) and reply.get("__error__"):
            raise RuntimeError(reply["data"])
        return reply

    def _pump_loop(self) -> None:
        poller = zmq.Poller()
        poller.register(self.sock, zmq.POLLIN)
        poller.register(self.direct_sock, zmq.POLLIN)
        poller.register(self._pump_wake_recv, zmq.POLLIN)
        # long idle timeout: poll wakes instantly on traffic; frequent
        # timer wakeups across many processes starve small hosts
        while not self._stopped.is_set():
            try:
                events = dict(poller.poll(timeout=1000))
            except zmq.ZMQError:
                break
            if self._pump_wake_recv in events:
                try:
                    while True:
                        self._pump_wake_recv.recv(zmq.NOBLOCK)
                except zmq.ZMQError:
                    pass
            if self.sock in events:
                while True:
                    try:
                        frames = self.sock.recv_multipart(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
                    try:
                        self._on_message(frames[0], P.loads(frames[1]))
                    except Exception:
                        logger.exception("%s: error handling %s", self.kind,
                                         frames[0])
            if self.direct_sock in events:
                while True:
                    try:
                        frames = self.direct_sock.recv_multipart(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
                    try:
                        # [sender identity, mtype, payload]
                        self._on_message(frames[1], P.loads(frames[2]),
                                         source=frames[0])
                    except Exception:
                        logger.exception("%s: error handling direct %s",
                                         self.kind, frames[1])

    def _on_message(self, mtype: bytes, m: dict, source=None) -> None:
        if self._chaos_dedup is not None and CH.check_dedup(
                self._chaos_dedup, m):
            return  # injected duplicate of a message already handled
        if self._reliable is not None:
            if mtype == P.MSG_ACK:
                self._reliable.on_ack(m)
                return
            # ``source`` routes the batched ack: None = controller
            # link, else the direct-channel sender's identity. Local
            # short-circuited sends are never stamped, so this no-ops.
            if self._reliable.on_receive(source, m):
                return  # retransmit duplicate of a handled message
        if mtype == P.MSG_BATCH:
            for sub_type, sub_payload in m["msgs"]:
                try:
                    self._on_message(sub_type, sub_payload, source)
                except Exception:
                    logger.exception("%s: error in batched %s", self.kind,
                                     sub_type)
            return
        if mtype == P.GENERIC_REPLY:
            self.replies.fulfill(m["rid"], m["data"])
        elif mtype == P.ERROR_REPLY:
            self.replies.fulfill(m["rid"], {"__error__": True, "data": m["data"]})
        elif mtype == P.TASK_RESULT:
            self._on_task_result(m)
        elif mtype in (P.TASK_DISPATCH, P.ACTOR_CALL, P.CANCEL_QUEUED):
            if mtype == P.CANCEL_QUEUED:
                m = dict(m, cancel_queued=True)
            if self.dispatch_handler is not None:
                self.dispatch_handler(m)
            else:
                # dispatched before the executor installed its handler
                # (registration reply races with first dispatch)
                self._early_dispatches.append(m)
        elif mtype == P.REGISTER_REPLY:
            self._register_reply = m
            self._register_ev.set()
        elif mtype == P.PUBSUB:
            for cb in self.pubsub_handlers.get(m["channel"], []) + \
                    self.pubsub_handlers.get("*", []):
                cb(m["channel"], m["data"])
        elif mtype == P.PG_UPDATE:
            with self.pg_cond:
                self.pg_events[m["pg_id"]] = m
                self.pg_cond.notify_all()
        elif mtype == P.RECONNECT:
            self._on_reconnect(m.get("gen"))
        elif mtype == P.FETCH_OBJECT:
            self._on_fetch_object(m)
        elif mtype == P.STREAM_ITEM:
            self._on_stream_item(m)
        elif mtype == P.STREAM_EOF:
            self._on_stream_eof(m)
        elif mtype == P.STREAM_CREDIT:
            if self.stream_credit_handler is not None:
                self.stream_credit_handler(m)
        elif mtype == P.TMPL_MISS:
            self._on_tmpl_miss(m)
        elif mtype == P.PROFILE_SELF:
            # sampling sleeps for the requested duration: never on the
            # pump thread
            threading.Thread(target=self._run_self_profile, args=(m,),
                             name="self-profile", daemon=True).start()
        elif mtype == P.LEASE_REVOKED:
            self._on_lease_revoked(m["worker"], m.get("dead", True))
        elif mtype == P.LEASE_GRANT:
            self._on_lease_grant(m.get("workers") or [])
        elif mtype == P.SHUTDOWN:
            self._stopped.set()

    def set_dispatch_handler(self, handler: Callable[[dict], None]) -> None:
        self.dispatch_handler = handler
        while self._early_dispatches:
            handler(self._early_dispatches.pop(0))

    def _register_msg(self) -> dict:
        m = {"kind": self.kind, "id": self.worker_id.binary(),
             "node_id": self.node_id.binary(), "pid": os.getpid()}
        if self._current_actor_id is not None:
            m["actor_id"] = self._current_actor_id.binary()
        if self.busy_probe is not None:
            try:
                m["busy"] = bool(self.busy_probe())
            except Exception:
                pass
        if self.kind == "driver" and self._register_ev.is_set():
            # re-registration keeps the assigned job identity (the default
            # job 0 before first registration must NOT be claimed)
            m["job_id"] = self.job_id.binary()
        return m

    def register(self, timeout: float = 30.0) -> dict:
        self._send(P.REGISTER, self._register_msg())
        if not self._register_ev.wait(timeout):
            raise TimeoutError("could not connect to controller")
        reply = self._register_reply
        if self.kind == "driver" and reply.get("job_id"):
            self.job_id = JobID(reply["job_id"])
            self._driver_task_id = TaskID.for_driver(self.job_id)
            self.current_task_id = self._driver_task_id
        return reply

    def _on_reconnect(self, gen: Optional[bytes]) -> None:
        """The controller restarted and lost its volatile state: re-send
        everything it needs from us, in one FIFO burst — identity first,
        then subscriptions, our live refcounts, and every unfinished task
        we own (reference: core workers/raylets resubscribe + resubmit on
        GCS restart; gcs_client reconnection path). At most once per
        controller generation: refcounts are absolute and tasks must not
        resubmit twice."""
        if gen is not None and gen == self._reconnect_gen:
            return
        self._reconnect_gen = gen
        logger.info("%s: controller restarted; re-announcing", self.kind)
        # worker leases died with the controller's grant table; the
        # inflight resubmit below covers direct tasks too
        with self._lease_lock:
            self._lease_pool.clear()
            self._lease_inflight.clear()
            self._direct_tids.clear()
            self._direct_backlog.clear()  # inflight resubmit covers them
            self._direct_backlog_bytes = 0
            self._lease_state = "none"
            # jittered: every driver re-leasing in lockstep against a
            # freshly-restarted controller is exactly the thundering
            # herd full jitter de-correlates
            self._lease_backoff_until = time.monotonic() + \
                self._lease_backoff.next_delay()
        self._send(P.REGISTER, self._register_msg())
        for channel in list(self.pubsub_handlers):
            if channel != "*":
                self._send(P.SUBSCRIBE, {"channel": channel})
        counts = self.reference_counter.all_counts()
        if counts:
            self._send(P.REF_DELTAS, {"deltas": counts})
        with self._inflight_lock:
            specs = list(self._inflight_specs.values())
        for spec in specs:
            if self._owner_local:
                # the resubmit runs controller-path: its results will be
                # directory-recorded, so the returns must be tracked
                for oid in spec.return_ids():
                    self.reference_counter.promote(oid)
            self._send(P.SUBMIT_TASK, {"spec": spec})
        # actor address long-polls in flight at the crash died with the
        # old controller's waiter lists: re-issue them or every call
        # queued behind RESOLVING hangs forever
        with self._actors_lock:
            resolving = [aid for aid, st in self._actors.items()
                         if st["state"] == "RESOLVING"]
        for aid in resolving:
            self._resolve_actor(aid)

    def shutdown(self) -> None:
        self._release_all_leases()
        self.reference_counter.flush()
        self.flush_timeline()
        self.recorder.flush()
        self.metrics_reporter.release()
        self._stopped.set()
        if self._reliable is not None:
            self._reliable.stop()
        self._cb_queue.put(None)
        # sentinel after the final enqueues: FIFO guarantees they flush
        self._out_q.put(None)
        self._flusher.join(timeout=2.0)
        try:
            self._pump_wake_send.send(b"", zmq.NOBLOCK)
        except Exception:
            pass
        self._pump.join(timeout=2.0)
        try:
            self.sock.close(0)
            self.direct_sock.close(0)
            for s, _ in self._peer_socks.values():
                s.close(0)
            self._peer_socks.clear()
            self._pump_wake_recv.close(0)
            self._pump_wake_send.close(0)
        except Exception:
            pass
        if self.shm:
            self.shm.close()

    # ------------------------------------------------------------- refcount
    def _flush_ref_deltas(self, deltas: Dict[bytes, int]) -> None:
        if self._stopped.is_set():
            return
        try:
            self._send(P.REF_DELTAS, {"deltas": deltas})
        except Exception:
            pass

    # ------------------------------------------------------------ put / get
    def put(self, value: Any, _owner_hint: Optional[bytes] = None) -> ObjectRef:
        with self._lock:
            self._put_counter += 1
            oid = ObjectID.for_put(self.current_task_id, self._put_counter)
        # store BEFORE creating the ref: inline values become owner-local
        # (no controller entry, no ref deltas) and the suppression must be
        # in place before the ref's +1 registers
        meta = self._store_value(oid, value, notify=True)
        if meta.get("node_id") is None and self._owner_local:
            b = oid.binary()
            self.reference_counter.mark_untracked(oid)
            with self._meta_lock:
                self._local_objects[b] = None
                self._meta[b] = meta
        ref = ObjectRef(oid, self.worker_id)
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            m = runtime_metrics()
            m.puts.inc()
            m.put_bytes.inc(meta.get("size", 0))
        except Exception:
            pass
        if meta.get("node_id") is not None and self.shm is not None \
                and hasattr(self.shm, "evict"):
            # shm-resident put owned by this process: eligible for eager
            # eviction unless its ref escapes (see mark_ref_escaped)
            with self._eager_lock:
                self._eager_owned[oid.binary()] = None
        return ref

    def mark_ref_escaped(self, object_id_b: bytes) -> None:
        """The ref was serialized (task arg, nested put, any pickle) —
        another process may now reference the object, so the owner must
        never free it unilaterally; the controller's global refcount is
        the authority from here on."""
        with self._eager_lock:
            self._eager_owned.pop(object_id_b, None)
            self._escaped_refs[object_id_b] = None
            while len(self._escaped_refs) > 65536:
                self._escaped_refs.popitem(last=False)
        if self._owner_local and \
                object_id_b in self.reference_counter._untracked:
            # unlocked pre-filter (common case: not ours / already
            # promoted); promote() re-checks under its lock
            self._promote_escaped(object_id_b)

    def _promote_escaped(self, object_id_b: bytes) -> None:
        """An owner-local ref is leaving this process: hand the object's
        lifecycle to the controller (inject our live count as deltas) and
        publish its value so borrowers and dep-parked tasks can resolve —
        the lazy analog of the PUT_OBJECT every put used to send."""
        n = self.reference_counter.promote(ObjectID(object_id_b))
        if n < 0:
            return
        with self._meta_lock:
            meta = self._meta.get(object_id_b)
            if meta is None:
                # result not here yet: publish the moment it lands
                self._publish_on_result[object_id_b] = None
        if meta is not None:
            self._publish_object(object_id_b, meta)

    def _publish_object(self, object_id_b: bytes, meta: dict) -> None:
        payload = {"object_id": object_id_b}
        for k in ("inline", "node_id", "size", "error"):
            v = meta.get(k)
            if v is not None:
                payload[k] = v
        self._send(P.PUT_OBJECT, payload)

    def _run_self_profile(self, m: dict) -> None:
        """Dashboard-requested self-profile (reference: the reporter
        agent's py-spy endpoint; this is the in-process sampler that
        needs no external tooling). Replies with collapsed stacks — the
        flamegraph input format."""
        try:
            from ray_tpu.util.profiling import sample_self
            s = sample_self(min(float(m.get("duration_s", 2.0)), 30.0),
                            interval_s=0.005)
            payload = {"rid": m.get("rid"), "collapsed": s.collapsed(),
                       "num_samples": s.num_samples,
                       "worker_id": self.worker_id.hex()}
        except Exception as e:  # noqa: BLE001
            payload = {"rid": m.get("rid"), "error": str(e)[:200]}
        self._send(P.PROFILE_RESULT, payload)

    def _on_fetch_object(self, m: dict) -> None:
        """Controller asks us (the owner) to publish an owner-local
        object a borrower is parked on."""
        b = m["object_id"]
        with self._meta_lock:
            meta = self._meta.get(b)
            if meta is None:
                self._publish_on_result[b] = None
        if meta is not None:
            self._publish_object(b, meta)

    def _on_owner_zero(self, oid: ObjectID) -> None:
        b = oid.binary()
        if self._owner_local:
            with self._meta_lock:
                was_local = self._local_objects.pop(b, False) is not False
                if was_local:
                    self._meta.pop(b, None)
            if was_local:
                # owner-local value: our copy is the only (or, if
                # escaped+published, a redundant) one — free it now.
                # NOTE _publish_on_result stays: an escaped-while-pending
                # borrower may still need the publish when it lands.
                self.memory_store.delete(oid)
                return
        with self._eager_lock:
            if b not in self._eager_owned or b in self._escaped_refs:
                return
            del self._eager_owned[b]
        try:
            freed = self.shm.evict(oid)
        except Exception:
            return
        if freed:
            with self._meta_lock:
                self._meta.pop(b, None)
            self.memory_store.delete(oid)
            if not self._stopped.is_set():
                try:
                    # deferrable: the extent is already recycled; the
                    # controller only drops bookkeeping
                    self._send_deferred(P.OWNER_FREE, {"object_ids": [b]})
                except Exception:
                    pass

    def _store_value(self, oid: ObjectID, value: Any, notify: bool) -> dict:
        """Serialize and store a value; returns result meta for TASK_DONE."""
        serialized = self.serialization.serialize(value)
        size = serialized.total_bytes()
        b = oid.binary()
        if size <= self.config.max_inline_object_size or self.shm is None:
            # small objects live in the in-process store (reference policy:
            # memory_store.h holds <100 KB objects only)
            self.memory_store.put(oid, value)
            blob = serialized.to_bytes()
            meta = {"object_id": b, "inline": blob, "size": size}
            if notify and not self._owner_local:
                # owner-local mode publishes lazily on ref escape
                # (mark_ref_escaped) instead of on every put
                self._send(P.PUT_OBJECT, {"object_id": b, "inline": blob})
        else:
            # large objects live ONLY in shm — duplicating the value in
            # process memory would double the footprint of every big put
            # (local gets deserialize zero-copy from the sealed extent)
            try:
                view = None
                deadline = time.monotonic() + \
                    self.config.store_full_timeout_s
                collected = False
                while True:
                    try:
                        view = self.shm.create(oid, size)
                        break
                    except ObjectStoreFullError:
                        # Queue behind eviction like plasma's create
                        # request queue (create_request_queue.h): ask
                        # the node authority to spill LRU objects, drop
                        # our own GC-deferred zero-copy values ONCE
                        # (their reader leases block spilling), and
                        # wait for in-flight executions elsewhere to
                        # release theirs.
                        if not collected:
                            collected = True
                            import gc
                            gc.collect()
                        self._node_store_rpc("make_room", bytes=size)
                        if time.monotonic() >= deadline:
                            from ray_tpu.core.native_store import (
                                STORE_DEBUG)
                            if STORE_DEBUG and hasattr(self.shm,
                                                       "_segment"):
                                seg = self.shm._segment()
                                rows = seg.list_sealed()
                                held = [(o.hex()[:12], sz, rc)
                                        for o, sz, rc in rows if rc > 0]
                                logger.warning(
                                    "STOREFULL inventory: %d sealed, "
                                    "%d reader-held (%d MB): %s",
                                    len(rows), len(held),
                                    sum(sz for _, sz, _ in held) >> 20,
                                    held[:40])
                            raise
                        time.sleep(0.2)
                serialized.write_to(view)
                self.shm.seal(oid)
            except FileExistsError:
                # duplicate execution (at-least-once after a controller
                # restart resubmitted a task that was already running):
                # the object is already here — keep the first copy
                pass
            meta = {"object_id": b, "node_id": self.node_id.binary(), "size": size}
            self.seed_meta(b, meta)
            if notify:
                self._send(P.PUT_OBJECT, {
                    "object_id": b, "node_id": self.node_id.binary(), "size": size})
        return meta

    def seed_meta(self, object_id_b: bytes, meta: dict) -> None:
        with self._meta_lock:
            self._meta[object_id_b] = meta

    def _restore_local(self, oid: ObjectID) -> Optional[memoryview]:
        """Restore a locally-spilled object and acquire a view,
        retrying while the node reports transient capacity pressure
        (segment full of reader-held extents). Returns None when the
        object is genuinely absent from this node."""
        deadline = time.monotonic() + self.config.store_full_timeout_s
        while True:
            try:
                # pid rides along so the node takes a reader lease FOR
                # US before replying: the extent cannot be re-spilled in
                # the reply->get_view window (the race that lost
                # over-budget shuffles under sustained spill thrash)
                reply = self._node_store_rpc(
                    "restore", object_id=oid.binary(), pid=os.getpid(),
                    timeout=60.0)
            except Exception:
                return None
            if reply.get("ok"):
                view = self.shm.get_view(oid, timeout=5.0)
                if reply.get("leased"):
                    # balance the node-held handshake lease now that we
                    # hold (or failed to take) our own
                    try:
                        self.shm._segment().release(oid)
                    except Exception:
                        pass
                if view is not None:
                    return view
                # re-spilled between reply and our lease: loop
            elif not reply.get("retry"):
                return None
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.3)

    def _node_store_rpc(self, op: str, timeout: float = 30.0,
                        **params) -> dict:
        """Blocking store-maintenance request to OUR node manager over
        the direct channel (make_room / restore spilled objects)."""
        rid = self.replies.new_request()
        node_identity = b"N" + self.node_id.binary()[:27]
        self._send_direct(node_identity, P.STORE_RPC,
                          dict(params, op=op, rid=rid))
        return self.replies.wait(rid, timeout, mtype=P.STORE_RPC) or {}

    def _on_task_result(self, m: dict) -> None:
        aid = m.get("actor_id")
        known = False
        if aid is not None:
            with self._actors_lock:
                st = self._actors.get(aid)
                if st is not None:
                    done_spec = st["inflight"].pop(m.get("task_id"), None)
                    known = known or done_spec is not None
                    self._unpin_task_args(done_spec)
        if m.get("task_id") is not None:
            with self._inflight_lock:
                done_spec = self._inflight_specs.pop(m["task_id"], None)
            known = known or done_spec is not None
            self._unpin_task_args(done_spec)
            self._on_direct_task_result(m["task_id"])
            st = self._stream_for(m["task_id"])
            if st is not None and m.get("error") is not None:
                # terminal failure of a streaming task (retries
                # exhausted / actor dead / cancelled): no more item
                # reports or replays are coming — fail the stream so
                # blocked consumers raise the typed error instead of
                # hanging on an index that will never arrive
                try:
                    st.fail(P.loads(m["error"]))
                except Exception:
                    from ray_tpu.exceptions import RayTpuError
                    st.fail(RayTpuError("streaming task failed"))
                self._drop_stream(m["task_id"])
        err = m.get("error")
        rc = self.reference_counter
        via_controller = m.get("via_controller")
        for r in m.get("results", []):
            b = r["object_id"]
            failed = err is not None or r.get("error") is not None
            publish = drop = local_mark = False
            # ---- refcount classification OUTSIDE _meta_lock: promote()
            # and local_count() can fire owner-zero, which takes
            # _meta_lock (observed self-deadlock on the pump thread) ----
            if self._owner_local:
                if err is not None and r.get("error") is None:
                    # carry the task error into the stored meta so a
                    # FETCH_OBJECT publish reproduces it for borrowers
                    # (the controller no longer records it)
                    r = dict(r, error=err)
                untracked = b in rc._untracked  # unlocked peek: promote
                # re-checks under its own lock
                if untracked and (via_controller
                                  or r.get("node_id") is not None):
                    # controller-path task (its directory records the
                    # results) or shm result (the extent is
                    # controller-side state): counts must flow
                    rc.promote(ObjectID(b))
                elif untracked:
                    local_mark = True  # stays owner-local
                else:
                    # promoted earlier (escape) or dead-before-arrival
                    with self._meta_lock:
                        pending_pub = b in self._publish_on_result
                    if not pending_pub and \
                            rc.local_count(ObjectID(b)) == 0:
                        drop = True
            with self._meta_lock:
                existing = self._meta.get(b)
                if not known and failed and existing is not None \
                        and existing.get("error") is None and (
                            existing.get("inline") is not None
                            or existing.get("node_id") is not None):
                    # duplicate execution (at-least-once resubmit raced
                    # a completion already in flight): first result
                    # wins — a duplicate failing on since-freed args
                    # must not poison good metas. Unknown-tid SUCCESS
                    # results still record: lineage reconstruction
                    # legitimately re-runs tasks whose spec we already
                    # retired.
                    continue
                if self._owner_local:
                    publish = b in self._publish_on_result
                    if publish:
                        del self._publish_on_result[b]
                        drop = False  # escaped meanwhile: must record
                    if drop:
                        # every ref died before the result arrived and
                        # nothing escaped: drop it. A shm extent (or a
                        # controller-recorded entry, for controller-path
                        # tasks) still exists — a 0-delta tells the
                        # controller the object lived and fully died.
                        pass
                    else:
                        if local_mark:
                            self._local_objects[b] = None
                        self._meta[b] = r
                else:
                    self._meta[b] = r
            if drop:
                if r.get("node_id") is not None or via_controller:
                    self._send(P.REF_DELTAS, {"deltas": {b: 0}})
                continue
            if publish:
                self._publish_object(b, r)
            oid = ObjectID(b)
            # materialize lazily at get(); but wake any waiter now
            self.memory_store.put(oid, _MetaReady(r))

    # ------------------------------------------------- streaming generators
    def submit_streaming_task(self, spec: TaskSpec):
        """Submit a ``num_returns="streaming"`` task and return the
        caller-side :class:`ObjectRefGenerator` (reference:
        ``CoreWorker::SubmitTask`` with ``returns_dynamically``). The
        stream record is registered BEFORE submission so the first
        ``STREAM_ITEM`` cannot race it."""
        from ray_tpu.core.streaming import ObjectRefGenerator, StreamState
        tid_b = spec.task_id.binary()
        if spec.trace is None:
            spec.trace = EV.child_trace(spec.task_id.hex())
        state = StreamState(self, tid_b)
        state.trace = spec.trace  # STREAM_CREDIT carries the link back
        with self._streams_lock:
            self._streams[tid_b] = state
        self.submit_task(spec)
        return ObjectRefGenerator(state)

    def _stream_for(self, tid_b: Optional[bytes]):
        if tid_b is None:
            return None
        with self._streams_lock:
            return self._streams.get(tid_b)

    def _drop_stream(self, tid_b: bytes) -> None:
        with self._streams_lock:
            self._streams.pop(tid_b, None)

    def _on_stream_item(self, m: dict) -> None:
        st = self._stream_for(m.get("task_id"))
        meta = m["meta"]
        if st is None:
            # not (or no longer) a stream we track: a lineage replay
            # re-reporting items whose stream was fully consumed, or a
            # borrower process. Seed the meta so parked gets resolve;
            # no stream bookkeeping, no ref minting.
            b = meta["object_id"]
            with self._meta_lock:
                self._meta[b] = meta
            self.memory_store.put(ObjectID(b), _MetaReady(meta), force=True)
            return
        st.on_item(m["index"], meta, m.get("worker"))

    def _on_stream_eof(self, m: dict) -> None:
        st = self._stream_for(m.get("task_id"))
        if st is not None:
            st.on_eof(m["count"], m.get("worker"))

    def _stream_send_credit(self, tid_b: bytes, consumed: int,
                            producer: Optional[bytes],
                            trace: Optional[tuple] = None) -> None:
        """Consumer progress report: cumulative, so loss-tolerant and
        idempotent; opens the producer's backpressure window."""
        if producer is None or self._stopped.is_set():
            return
        self._send_direct(producer, P.STREAM_CREDIT,
                          {"task_id": tid_b, "consumed": consumed,
                           "trace": trace})

    def _stream_finished(self, tid_b: bytes) -> None:
        """StreamState hook: the consumer reached EOF — drop the routing
        record (late lineage replays fall back to plain meta seeding)."""
        self._drop_stream(tid_b)

    def _close_stream(self, state) -> None:
        """Early consumer termination: drop buffered item refs, cancel
        the producer, forget the stream."""
        tid_b = state.task_id_b
        already_done = state.eof_index is not None and state.error is None \
            and not state.items
        refs = state.close()
        self._drop_stream(tid_b)
        # dropping the buffered refs is what frees unconsumed items —
        # each was +1'd at report time; the consumer never took them
        del refs
        with self._inflight_lock:
            self._inflight_specs.pop(tid_b, None)
        if not already_done and not self._stopped.is_set():
            # cancel the producer (it may still be yielding into the
            # backpressure window); route like any task cancel
            try:
                ref = ObjectRef(ObjectID.for_task_return(TaskID(tid_b), 1),
                                self.worker_id, _register=False)
                self.cancel(ref, force=False)
            except Exception:
                logger.exception("stream cancel failed")

    @staticmethod
    def _find_weakref_targets(value, depth: int = 3) -> list:
        return _weakref_targets(value, depth)

    def _unpin_task_args(self, spec) -> None:
        """Balance add_submitted_task_ref once the task's result is in:
        the arg pin exists so an arg object can't be freed while its
        consumer is still in flight. Without the release every task-arg
        object stays pinned (count never reaches zero) and its extent
        leaks for the session's lifetime."""
        if spec is None:
            return
        for _, oid in spec.arg_refs:
            self.reference_counter.remove_submitted_task_ref(oid)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            out.append(self._get_one(ref, remaining))
        return out[0] if single else out

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]):
        """Dual-path get (reference: CoreWorker::GetObjects dual-path
        memory-store/plasma resolution, core_worker.cc:1478): try the
        in-process store, then local shm, then ask the controller for the
        location (which blocks server-side until the object exists and is
        local, triggering transfer/reconstruction as needed)."""
        oid = ref.id()
        b = oid.binary()
        found, value = self.memory_store.try_get(oid)
        if found and not isinstance(value, _MetaReady):
            return value
        if isinstance(value, _MetaReady):
            return self._materialize(oid, value.meta)
        with self._meta_lock:
            meta = self._meta.get(b)
        if meta is not None:
            return self._materialize(oid, meta)
        if self.shm is not None and self.shm.contains(oid):
            return self._materialize(
                oid, {"object_id": b, "node_id": self.node_id.binary()})
        # Not local: if we own the object its TASK_RESULT will be pushed to
        # us; otherwise ask the controller (async; reply lands in the memory
        # store as _MetaReady). Block with the caller's timeout either way.
        owned = ref.owner is not None and ref.owner == self.worker_id
        if not owned:
            self._ensure_location_probe(
                b, ref.owner.binary() if ref.owner is not None else None)
        from ray_tpu.core.memory_store import WeakCacheExpired
        token = self._enter_blocked()
        try:
            if owned:
                # grace-then-probe: the direct TASK_RESULT push normally
                # lands in ms, but if it was lost (producer killed with
                # the result still in its send queue) waiting on it alone
                # hangs forever — fall back to asking the controller,
                # which answers from its task table, reconstructs via
                # lineage, or fails the object loudly.
                from ray_tpu.exceptions import GetTimeoutError
                grace = 5.0 if timeout is None else min(5.0, timeout)
                try:
                    value = self.memory_store.get(oid, grace)
                except GetTimeoutError:
                    self._ensure_location_probe(b)
                    rest = None if timeout is None else timeout - grace
                    value = self.memory_store.get(oid, rest)
            else:
                value = self.memory_store.get(oid, timeout)
        except WeakCacheExpired:
            # the value existed, was weak-cached, and got collected
            # between our checks — re-materialize from shm via meta
            # (the finally below balances _enter_blocked exactly once)
            return self._get_one(ref, timeout)
        finally:
            self._exit_blocked(token)
        if isinstance(value, _MetaReady):
            value = self._materialize(oid, value.meta)
        return value

    def _enter_blocked(self) -> bool:
        """Blocked-worker protocol: a task about to wait on a remote result
        hands its unstarted pipeline back and releases its cpu so the
        cluster keeps making progress (avoids nested-task deadlock)."""
        nb = self.block_notifier
        if nb is None:
            return False
        tid = getattr(self._task_ctx, "task_id", None)
        if tid is None or tid == self._driver_task_id:
            return False
        return nb.on_block()

    def _exit_blocked(self, token: bool) -> None:
        if token and self.block_notifier is not None:
            self.block_notifier.on_unblock()

    @staticmethod
    def _count_materialized(nbytes: int) -> None:
        """Inbound transfer accounting: bytes of object payload this
        process pulled in to satisfy a get (the pipeline train-mode
        tests assert the driver's per-step inbound stays scalar-sized
        — no grad/param bytes through the driver)."""
        try:
            from ray_tpu.core.metric_defs import runtime_metrics
            runtime_metrics().materialized_bytes.inc(nbytes)
        except Exception:
            pass

    def _materialize(self, oid: ObjectID, meta: dict):
        if meta.get("error") is not None:
            err = P.loads(meta["error"])
            self.memory_store.put(oid, None, error=err, force=True)
            raise err
        if meta.get("inline") is not None:
            value, _ = self.serialization.deserialize_from_view(
                memoryview(meta["inline"]))
            self.memory_store.put(oid, value, force=True)
            self._count_materialized(len(meta["inline"]))
            return value
        # shared-memory object
        node_b = meta.get("node_id")
        if self.shm is not None and (node_b == self.node_id.binary()
                                     or self.shm.contains(oid)):
            # fast probe first: a locally-SPILLED object will never
            # appear however long we poll — restore it instead of
            # burning the full timeout (background eviction makes
            # spilled-but-local routine)
            view = self.shm.get_view(oid, timeout=0.05)
            if view is None and node_b == self.node_id.binary():
                # not in the segment but supposedly local: it may have
                # been spilled to disk — ask the node to restore it
                # (reference: AsyncRestoreSpilledObject before a local
                # plasma get gives up)
                view = self._restore_local(oid)
            if view is not None:
                value, _, bufs = \
                    self.serialization.deserialize_from_view_tracked(view)
                self._count_materialized(view.nbytes)
                self._cache_shm_value(oid, value, bufs)
                return value
        # remote: ask controller to make it local (or hand us inline
        # bytes). Bounded retry loop: the reply only lands once the
        # object is supposedly local, but the local copy can be a
        # disk-faulted spill — the node reports the stale holder
        # (PULL_FAILED) while we re-ask, and the controller re-pulls
        # from another holder / reconstructs before answering again.
        # Only after the retries is the typed ObjectLostError raised.
        for attempt in range(3):
            reply = self.request(P.GET_LOCATION, {
                "object_id": oid.binary(),
                "want_node": self.node_id.binary()},
                timeout=self.config.rpc_timeout_s * 4)
            if reply.get("error") is not None:
                err = P.loads(reply["error"])
                self.memory_store.put(oid, None, error=err, force=True)
                raise err
            if reply.get("inline") is not None:
                value, _ = self.serialization.deserialize_from_view(
                    memoryview(reply["inline"]))
                self.memory_store.put(oid, value, force=True)
                self._count_materialized(len(reply["inline"]))
                return value
            if self.shm is None:
                raise RuntimeError(
                    "no shm store attached; cannot fetch object")
            view = self.shm.get_view(oid, timeout=2.0)
            if view is None:
                view = self._restore_local(oid)
            if view is not None:
                value, _, bufs = \
                    self.serialization.deserialize_from_view_tracked(view)
                self._count_materialized(view.nbytes)
                self._cache_shm_value(oid, value, bufs)
                return value
            time.sleep(0.2 * (attempt + 1))
        from ray_tpu.exceptions import ObjectLostError
        raise ObjectLostError(oid)

    def _cache_shm_value(self, oid: ObjectID, value: Any,
                         buffer_views: Optional[list] = None) -> None:
        """Cache a zero-copy shm value WEAKLY and release the reader
        ledger when the last ALIAS of the extent dies (reference:
        plasma buffers pin an object only while the client still holds
        them). A strong cache would pin the extent for the process
        lifetime — every large task arg a worker ever saw would leak.

        The release anchors are the out-of-band BUFFER VIEWS from
        deserialization: arrow buffers and numpy bases reference
        exactly these memoryview objects, so they die — by refcount,
        no gc needed — precisely when the last table slice / array
        view / concat product is gone. Finalizing on the VALUE is both
        too early (a table can die while its buffers live on inside
        derived objects — data corruption once the extent recycles)
        and too late (arrow tables sit in reference cycles, so a busy
        process pins consumed blocks until some distant gen-2 GC)."""
        import weakref
        anchors = list(buffer_views or ())
        if not anchors:
            # legacy path (no tracked buffers): walk the value
            anchors = _weakref_targets(value)
        if not anchors:
            # nothing aliases the extent (pure-copy value): release the
            # ledger now and cache strongly
            self.memory_store.put(oid, value, force=True)
            self.shm.release(oid)
            return
        remaining = [len(anchors)]
        shm = self.shm

        def _release(_=None):
            remaining[0] -= 1
            if remaining[0] == 0:
                try:
                    shm.release(oid)
                except Exception:
                    pass

        for t in anchors:
            weakref.finalize(t, _release)
        self.memory_store.put(oid, value, force=True, weak=True)

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """Event-driven wait: one ``on_ready`` hook per pending ref trips a
        counter; no polling loop, no per-ref threads (reference:
        CoreWorker::Wait's fused memory-store/plasma waiter,
        core_worker.cc:1807)."""
        if num_returns > len(refs):
            raise ValueError(
                f"num_returns ({num_returns}) exceeds the number of refs "
                f"({len(refs)})")
        done = threading.Event()
        lock = threading.Lock()
        ready_flags = [False] * len(refs)
        count = [0]
        hooked: List[Tuple[ObjectID, Callable]] = []

        def _mark(i: int) -> None:
            with lock:
                if ready_flags[i]:
                    return
                ready_flags[i] = True
                count[0] += 1
                if count[0] >= num_returns:
                    done.set()

        for i, ref in enumerate(refs):
            oid = ref.id()
            b = oid.binary()
            with self._meta_lock:
                have_meta = b in self._meta
            if have_meta or self.memory_store.contains(oid):
                _mark(i)
                continue
            cb = (lambda i: lambda value, error: _mark(i))(i)
            hooked.append((oid, cb))
            self.memory_store.on_ready(oid, cb)
            if ref.owner is None or ref.owner != self.worker_id:
                self._ensure_location_probe(
                    b, ref.owner.binary() if ref.owner is not None else None)
        with lock:
            if count[0] >= num_returns:
                done.set()
        if not done.is_set():
            token = self._enter_blocked()
            try:
                done.wait(timeout)
            finally:
                self._exit_blocked(token)
        for oid, cb in hooked:
            self.memory_store.remove_callback(oid, cb)
        ready: List[ObjectRef] = []
        pending: List[ObjectRef] = []
        with lock:
            for i, ref in enumerate(refs):
                if ready_flags[i] and len(ready) < num_returns:
                    ready.append(ref)
                else:
                    pending.append(ref)
        return ready, pending

    def _ensure_location_probe(self, object_id_b: bytes,
                               owner_b: Optional[bytes] = None) -> None:
        """Ask the controller (once) where an object lives; the reply lands
        in the meta table + memory store from the pump thread. The
        controller holds the request server-side until the object exists,
        so this doubles as a remote-completion subscription. A stale probe
        (no reply within the retry window — e.g. the message was dropped)
        is re-issued rather than wedging the object forever; the abandoned
        ReplyWaiter callback entry is bounded to one per window."""
        now = time.monotonic()
        with self._meta_lock:
            if object_id_b in self._meta:
                return
            started = self._pending_locations.get(object_id_b)
            if started is not None and \
                    now - started < self.config.rpc_timeout_s * 4:
                return
            self._pending_locations[object_id_b] = now

        def on_reply(reply, b=object_id_b):
            with self._meta_lock:
                self._meta[b] = reply
                self._pending_locations.pop(b, None)
            self.memory_store.put(ObjectID(b), _MetaReady(reply))

        rid = self.replies.new_request(callback=on_reply)
        msg = {"object_id": object_id_b, "rid": rid,
               "want_node": self.node_id.binary()}
        if owner_b is not None:
            # lets the controller fetch an owner-local object's value
            # from its owner when the directory has no entry
            msg["owner"] = owner_b
        self._send(P.GET_LOCATION, msg)

    def register_completion_callback(self, ref: ObjectRef, cb: Callable) -> None:
        oid = ref.id()

        def materialize_and_call(value, error):
            from ray_tpu.core.memory_store import WeakExpired
            if isinstance(value, WeakExpired):
                with self._meta_lock:
                    meta = self._meta.get(oid.binary())
                if meta is None:
                    # locally-materialized object with no recorded meta:
                    # the bytes are still in the local store
                    meta = {"object_id": oid.binary(),
                            "node_id": self.node_id.binary()}
                value = _MetaReady(meta)
            if isinstance(value, _MetaReady):
                try:
                    value = self._materialize(oid, value.meta)
                    error = None
                except BaseException as e:  # noqa: BLE001
                    value, error = None, e
            cb(value, error)

        def wrapper(value, error):
            # hop off the pump thread: materialization may issue blocking
            # RPCs that only the pump can fulfill
            self._cb_queue.put(lambda: materialize_and_call(value, error))

        # large own puts live only in shm (meta seeded, store empty):
        # complete immediately instead of waiting on a store event
        with self._meta_lock:
            meta = self._meta.get(oid.binary())
        if meta is not None and not self.memory_store.contains(oid):
            wrapper(_MetaReady(meta), None)
            return
        self.memory_store.on_ready(oid, wrapper)

    # ---------------------------------------------------------- submission
    def next_task_id(self) -> TaskID:
        return TaskID.for_normal_task(self.job_id)

    def serialize_args(self, args: tuple, kwargs: dict
                       ) -> Tuple[bytes, List[Tuple[int, ObjectID]], List[ObjectID]]:
        """Top-level ObjectRef args become placeholders resolved pre-exec
        (reference: dependency_resolver.cc); nested refs stay borrowed."""
        if not args and not kwargs:
            # no-arg calls dominate fan-out workloads: one cached blob
            # instead of a fresh cloudpickle Pickler per submission
            blob = self._empty_args_blob
            if blob is None:
                blob = self._empty_args_blob = \
                    self.serialization.serialize(((), {})).to_bytes()
            return blob, [], []
        arg_refs: List[Tuple[int, ObjectID]] = []
        new_args = []
        for i, a in enumerate(args):
            if isinstance(a, ObjectRef):
                arg_refs.append((len(arg_refs), a.id()))
                new_args.append(_ArgPlaceholder(len(arg_refs) - 1))
            else:
                new_args.append(a)
        new_kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, ObjectRef):
                arg_refs.append((len(arg_refs), v.id()))
                new_kwargs[k] = _ArgPlaceholder(len(arg_refs) - 1)
            else:
                new_kwargs[k] = v
        serialized = self.serialization.serialize((tuple(new_args), new_kwargs))
        contained = [r.id() for r in serialized.contained_refs]
        return serialized.to_bytes(), arg_refs, contained

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        spec.owner = self.worker_id
        if spec.trace is None:
            # causal trace propagation: inherit the submitting thread's
            # context (a task executing under a propagated trace, or a
            # tracing.span) — else this task roots a new trace
            spec.trace = EV.child_trace(spec.task_id.hex())
        # register return refs against OUR counter directly — the
        # ObjectRef ctor's context lookup (global-worker resolve per
        # ref) is measurable on the fan-out hot path
        rc = self.reference_counter
        refs = []
        owner_local = self._owner_local
        for oid in spec.return_ids():
            if owner_local:
                # returns start owner-local (suppressed deltas); promoted
                # below if the task spills to the controller path, at
                # result arrival if the result is shm, or on ref escape
                rc.mark_untracked(oid)
            r = ObjectRef(oid, self.worker_id, _register=False)
            rc.add_local_reference(r)
            r._registered = True
            refs.append(r)
        for _, oid in spec.arg_refs:
            self.reference_counter.add_submitted_task_ref(oid)
            if owner_local and oid.binary() in rc._untracked:
                # a top-level arg ref leaves this process without being
                # pickled (it rides spec.arg_refs as a raw id): that is
                # an escape — the consumer and any dep-parking need the
                # object controller-visible. Shm objects are already
                # directory-tracked; only owner-local ones promote.
                self._promote_escaped(oid.binary())
        # deltas ride the threshold/periodic flush — flushing per submit
        # would cost a REF_DELTAS apply per task on the controller loop
        if spec.is_actor_task:
            self._submit_actor_task(spec)
        else:
            # owner-side pending record: a restarted controller has no
            # task table, so WE resubmit on RECONNECT (reference: the
            # owning core worker holds the spec, not the GCS)
            with self._inflight_lock:
                self._inflight_specs[spec.task_id.binary()] = spec
            if not self._try_direct_submit(spec):
                if owner_local:
                    # controller-path task: the controller records its
                    # results in the directory, so the return refs must
                    # be controller-tracked from the start
                    for oid in spec.return_ids():
                        rc.promote(oid)
                if spec.arg_refs:
                    # owner-side dependency seeding: attach what we know
                    # about arg objects so the controller can resolve
                    # deps it never learned of (a producer killed with
                    # its TASK_DONE unflushed leaves a directory hole;
                    # our direct TASK_RESULT still recorded the meta)
                    metas = {}
                    with self._meta_lock:
                        for _, oid in spec.arg_refs:
                            am = self._meta.get(oid.binary())
                            if am and am.get("error") is None and (
                                    am.get("node_id") is not None
                                    or (am.get("inline") is not None
                                        and len(am["inline"]) <= 1 << 16)):
                                metas[oid.binary()] = am
                    if metas:
                        spec.arg_metas = metas
                self._send(P.SUBMIT_TASK, {"spec": spec})
        self._record_event(spec, "submitted")
        self.recorder.record_task(
            EV.SUBMITTED, spec.task_id.hex(), spec.trace,
            name=spec.name or spec.function.qualname)
        return refs

    # ---------------------------------------------- direct normal tasks
    def _try_direct_submit(self, spec: TaskSpec) -> bool:
        """Push a dependency-free default-shape task straight to a
        leased worker. Returns False when the controller path should
        handle it (deps, placement constraints, custom resources, no
        lease capacity)."""
        if self.kind != "driver" or spec.arg_refs \
                or spec.is_actor_creation \
                or spec.scheduling_strategy.kind != "DEFAULT":
            return False
        res = spec.resources
        if res and (set(res) - {"CPU"} or res.get("CPU", 1.0) > 1.0):
            return False
        with self._lease_lock:
            if self._lease_state == "none":
                if time.monotonic() >= self._lease_backoff_until:
                    self._lease_state = "pending"
                    self._request_leases()
                return False
            if self._lease_state == "pending":
                # grant in flight: commit the burst to the direct path
                # now — spilling to the controller while every CPU is
                # about to be lease-held just feeds the starvation
                # reclaimer (revoke/grant thrash measured at ~2.4x the
                # per-task cost of waiting for the grant)
                return self._backlog_locked(spec)
            if self._lease_state != "ready" or not self._lease_pool:
                return False
            w = self._pick_leased_worker_locked()
            if w is None:
                # saturated: queue locally and drain on completions.
                # The caps bound driver memory, not throughput — the
                # controller path dispatches to the same workers but
                # costs ~3 extra controller-loop hops per task, so it
                # only wins once the backlog is pathological. A growing
                # backlog also re-requests leases sized to demand so a
                # big cluster's idle workers are drawn into the pool
                # (the controller parks what it can't grant yet).
                took = self._backlog_locked(spec)
                if took and not self._lease_req_inflight and \
                        time.monotonic() >= self._lease_topup_backoff and \
                        len(self._direct_backlog) > \
                        len(self._lease_pool) * \
                        self.config.dispatch_pipeline_depth:
                    self._request_leases(self._lease_want_locked())
                return took
            self._direct_tids[spec.task_id.binary()] = w
        self._dispatch_direct(w, spec)
        return True

    def _dispatch_direct(self, w: bytes, spec: TaskSpec) -> None:
        """Peer-to-peer dispatch onto a leased worker (one site for the
        DISPATCHED flight-recorder event)."""
        self.recorder.record_task(EV.DISPATCHED, spec.task_id.hex(),
                                  spec.trace, worker=w.hex()[:12])
        self._send_direct(w, P.TASK_DISPATCH,
                          {"spec": spec, "driver_leased": True})

    def _pick_leased_worker_locked(self) -> Optional[bytes]:
        depth = self.config.dispatch_pipeline_depth
        best, best_n = None, depth
        for w in self._lease_pool:
            n = self._lease_inflight.get(w, 0)
            if n < best_n:
                best, best_n = w, n
        if best is not None:
            self._lease_inflight[best] = best_n + 1
        return best

    def _backlog_locked(self, spec: TaskSpec) -> bool:
        """Caller holds _lease_lock: queue a spec for the direct path if
        the count/byte caps allow. Returns False to spill to the
        controller instead."""
        if len(self._direct_backlog) >= self._direct_backlog_cap or \
                self._direct_backlog_bytes >= \
                self._direct_backlog_bytes_cap:
            return False
        self._direct_backlog.append(spec)
        self._direct_backlog_bytes += len(spec.args_blob) + 512
        return True

    def _pop_backlog_locked(self) -> TaskSpec:
        spec = self._direct_backlog.popleft()
        self._direct_backlog_bytes -= len(spec.args_blob) + 512
        if not self._direct_backlog:
            self._direct_backlog_bytes = 0
        return spec

    def _lease_want_locked(self) -> int:
        """How many leases demand justifies: enough workers to cover the
        backlog at the configured pipeline depth, within sane bounds."""
        depth = max(1, self.config.dispatch_pipeline_depth)
        want = (len(self._direct_backlog) + depth - 1) // depth
        return max(4, min(1024, want))

    def _drain_backlog_locked(self) -> List[Tuple[bytes, TaskSpec]]:
        """Caller holds _lease_lock: assign backlogged specs to leased
        workers up to the pipeline depth; returns the dispatches."""
        sends = []
        while self._direct_backlog and self._lease_pool:
            w = self._pick_leased_worker_locked()
            if w is None:
                break
            spec = self._pop_backlog_locked()
            self._direct_tids[spec.task_id.binary()] = w
            sends.append((w, spec))
        return sends

    def _request_leases(self, count: int = 4) -> None:
        self._lease_req_inflight = True

        def on_reply(reply):
            workers = (reply or {}).get("workers") or []
            spill: List[TaskSpec] = []
            sends: List[Tuple[bytes, TaskSpec]] = []
            with self._lease_lock:
                self._lease_req_inflight = False
                if workers:
                    self._lease_pool.extend(workers)
                    self._lease_state = "ready"
                    self._lease_backoff.reset()
                    self._topup_backoff.reset()
                    # tasks backlogged while this request was in
                    # flight: dispatch onto the fresh capacity NOW —
                    # with no direct tasks inflight there are no
                    # completions to drain them otherwise
                    sends = self._drain_backlog_locked()
                elif self._lease_pool:
                    # empty TOP-UP grant: the cluster is fully leased
                    # (usually by us). We still hold workers with tasks
                    # in flight, so completions WILL drain the backlog
                    # at direct-path cost — spilling it to the
                    # controller here ping-pongs ~half of every big
                    # burst onto the slow path (measured: 1012/2000
                    # spilled, tasks_async capped at ~4.4k/s). Keep the
                    # pool, just stop re-asking for a while (growing,
                    # jittered: repeat empty grants back off further).
                    self._lease_topup_backoff = time.monotonic() + \
                        self._topup_backoff.next_delay()
                else:
                    # nothing grantable and we hold no capacity at all;
                    # retry later. Tasks optimistically backlogged while
                    # the request was in flight must not starve — route
                    # them through the controller after all.
                    self._lease_state = "none"
                    self._lease_backoff_until = time.monotonic() + \
                        self._lease_backoff.next_delay()
                    while self._direct_backlog:
                        spill.append(self._pop_backlog_locked())
            for w, spec in sends:
                self._dispatch_direct(w, spec)
            for spec in spill:
                if self._owner_local:
                    # spilling to the controller path: returns become
                    # directory-recorded — track them
                    for oid in spec.return_ids():
                        self.reference_counter.promote(oid)
                self._send(P.SUBMIT_TASK, {"spec": spec})

        rid = self.replies.new_request(callback=on_reply)
        self._send(P.LEASE_WORKERS, {"count": count, "rid": rid})

    def _on_lease_grant(self, workers: List[bytes]) -> None:
        """Deferred grant arrived (parked request): extend the pool and
        drain backlog onto the new capacity."""
        with self._lease_lock:
            self._lease_pool.extend(workers)
            if self._lease_pool:
                self._lease_state = "ready"
                self._lease_backoff.reset()
            sends = self._drain_backlog_locked()
        for w, spec in sends:
            self._dispatch_direct(w, spec)

    def _on_direct_task_result(self, tid_b: bytes) -> None:
        send = None
        with self._lease_lock:
            w = self._direct_tids.pop(tid_b, None)
            if w is not None and w in self._lease_inflight:
                n = self._lease_inflight[w] - 1
                if n <= 0:
                    self._lease_inflight.pop(w, None)
                else:
                    self._lease_inflight[w] = n
            if self._direct_backlog and self._lease_pool:
                nxt = self._pick_leased_worker_locked()
                if nxt is not None:
                    spec = self._pop_backlog_locked()
                    self._direct_tids[spec.task_id.binary()] = nxt
                    send = (nxt, spec)
        if send is not None:
            self._dispatch_direct(send[0], send[1])

    def _on_lease_revoked(self, worker: bytes,
                          dead: bool = True) -> None:
        """The controller took a leased worker back. If the worker DIED,
        resubmit its in-flight specs via the controller path (anything
        still tracked here never reported a result). If it was merely
        reclaimed (queue starvation), its queued direct tasks still
        complete — just stop sending it new ones."""
        if dead and self._reliable is not None:
            # peer-death notice: the resubmit below IS the recovery;
            # retransmitting into a dead worker only delays it
            self._reliable.drop_target(worker)
        resubmit: List[TaskSpec] = []
        with self._lease_lock:
            try:
                self._lease_pool.remove(worker)
            except ValueError:
                pass
            if dead:
                self._lease_inflight.pop(worker, None)
                lost = [tid for tid, w in self._direct_tids.items()
                        if w == worker]
                for tid in lost:
                    del self._direct_tids[tid]
            else:
                lost = []
            if not self._lease_pool:
                self._lease_state = "none"
                self._lease_backoff_until = time.monotonic() + \
                    self._lease_backoff.next_delay()
                # no leases left: the local backlog would never drain
                while self._direct_backlog:
                    resubmit.append(self._pop_backlog_locked())
        with self._inflight_lock:
            for tid in lost:
                spec = self._inflight_specs.get(tid)
                if spec is not None:
                    resubmit.append(spec)
        for spec in resubmit:
            if self._owner_local:
                for oid in spec.return_ids():
                    self.reference_counter.promote(oid)
            self._send(P.SUBMIT_TASK, {"spec": spec})

    def _release_all_leases(self) -> None:
        with self._lease_lock:
            pool, self._lease_pool = self._lease_pool, []
            self._lease_state = "none"
            self._lease_inflight.clear()
            self._direct_tids.clear()
            backlog = list(self._direct_backlog)
            self._direct_backlog.clear()
            self._direct_backlog_bytes = 0
        for spec in backlog:
            if self._owner_local:
                for oid in spec.return_ids():
                    self.reference_counter.promote(oid)
            self._send(P.SUBMIT_TASK, {"spec": spec})
        if pool:
            try:
                self._send(P.RELEASE_LEASES, {"workers": pool})
            except Exception:
                pass

    # ------------------------------------------------- direct actor calls
    def _submit_actor_task(self, spec: TaskSpec) -> None:
        """Client-side actor submitter (reference:
        CoreWorkerDirectActorTaskSubmitter, direct_actor_task_submitter.h):
        queue until the actor's worker address resolves, then push calls
        directly to that worker — the controller is only consulted for the
        address (long-poll held until ALIVE) and for liveness pubsub."""
        aid = spec.actor_id.binary()
        action = None  # ("dead", err) | "resolve" | "queued" | "sent"
        with self._actors_lock:
            st = self._actors.get(aid)
            if st is None:
                st = self._actors[aid] = {
                    "state": "RESOLVING", "worker": None, "queue": [],
                    "inflight": {}, "error": None, "tmpls": {}}
                st["queue"].append(spec)
                action = "resolve"
            elif st["state"] == "DIRECT":
                st["inflight"][spec.task_id.binary()] = spec
                # enqueue INSIDE the lock: template registration and its
                # compact calls must hit the peer channel in assignment
                # order, or the worker sees a compact call it can't
                # expand
                self._send_direct(st["worker"], P.ACTOR_CALL,
                                  self._actor_call_msg(st, spec))
                action = "sent"
            elif st["state"] == "DEAD":
                action = ("dead", st["error"])
            else:  # RESOLVING
                st["queue"].append(spec)
                action = "queued"
        if action == "resolve":
            self._resolve_actor(aid)
        elif isinstance(action, tuple) and action[0] == "dead":
            self._fail_actor_task_local(spec, action[1])

    def _on_tmpl_miss(self, m: dict) -> None:
        """The actor worker lost the template for a compact call
        (evicted, or the registration message was dropped): resend that
        call with its FULL spec — which also re-registers the template
        for subsequent compact calls. Without this the dropped call
        would hang its ray.get forever."""
        tid_b = m.get("task_id") or b""
        with self._actors_lock:
            for st in self._actors.values():
                spec = st["inflight"].get(tid_b)
                if spec is not None and st["state"] == "DIRECT":
                    # the worker's view of our templates is stale: start
                    # a fresh numbering so every method re-registers,
                    # then resend this call full (which re-registers its
                    # own template in the same message)
                    st["tmpls"] = {}
                    self._send_direct(
                        st["worker"], P.ACTOR_CALL,
                        self._actor_call_msg(st, spec, keep_seq=True))
                    return

    def _actor_call_msg(self, st: dict, spec: TaskSpec,
                        keep_seq: bool = False) -> dict:
        """Wire form of one actor call. The spec is mostly static per
        method: ship it once as a TEMPLATE, then only the dynamic fields
        (reference: the submitter's push_normal_task payload is protobuf
        with the same static/dynamic split done by field encoding).
        Caller holds _actors_lock.

        Sequence numbers are assigned HERE, at send time, one monotonic
        stream per (this caller, actor incarnation) — reference:
        CoreWorkerDirectActorTaskSubmitter's seq_no. The actor-side
        sequencer (worker._CallSequencer) uses them to execute calls in
        submission order even when the reliable layer's retransmits
        deliver them out of order. ``keep_seq`` re-sends (TMPL_MISS)
        reuse the call's original seq: the worker dropped that compact
        call BEFORE sequencing, so the resend must fill its own slot —
        a fresh seq would leave a permanent gap."""
        if not keep_seq:
            st["seq"] = st.get("seq", 0) + 1
            spec.sequence_number = st["seq"]
        if spec.runtime_env or spec.resources:
            # rare per-call variability: don't template
            return {"spec": spec}
        key = (spec.function, spec.name, spec.num_returns,
               spec.max_retries, spec.retry_exceptions,
               spec.concurrency_group, spec.backpressure)
        tmpls = st["tmpls"]
        tid = tmpls.get(key)
        me = self.worker_id.binary()
        if tid is None:
            tid = tmpls[key] = len(tmpls) + 1
            return {"spec": spec, "tmpl": tid, "caller": me}
        return {"tmpl": tid, "caller": me,
                "task_id": spec.task_id.binary(),
                "seq": spec.sequence_number,
                "args_blob": spec.args_blob,
                "arg_refs": spec.arg_refs or None,
                "arg_metas": spec.arg_metas,
                # the template's trace is the FIRST call's — each
                # compact call must carry its own causal link
                "trace": spec.trace}

    def _resolve_actor(self, aid: bytes) -> None:
        hexid = ActorID(aid).hex()
        channel = f"actor:{hexid}"
        if channel not in self.pubsub_handlers:
            self.subscribe(channel,
                           lambda ch, data, aid=aid: self._on_actor_update(aid, data))
        rid = self.replies.new_request(
            callback=lambda reply, aid=aid: self._on_actor_addr(aid, reply))
        self._send(P.ACTOR_ADDR, {"actor_id": aid, "rid": rid})

    def _on_actor_addr(self, aid: bytes, reply: Any) -> None:
        """Pump-thread callback: the controller answered the address
        long-poll (actor ALIVE on some worker, or dead)."""
        to_send: List[TaskSpec] = []
        to_fail: List[TaskSpec] = []
        err = None
        worker = None
        with self._actors_lock:
            st = self._actors.get(aid)
            if st is None or st["state"] == "DEAD":
                return
            bad = not isinstance(reply, dict) or reply.get("__error__") \
                or reply.get("dead")
            if bad:
                from ray_tpu.exceptions import ActorDiedError
                if isinstance(reply, dict) and reply.get("error"):
                    err = P.loads(reply["error"])
                else:
                    err = ActorDiedError(ActorID(aid), "actor is dead")
                st["state"] = "DEAD"
                st["error"] = err
                to_fail = st["queue"] + list(st["inflight"].values())
                st["queue"] = []
                st["inflight"] = {}
            else:
                worker = reply["worker"]
                st["state"] = "DIRECT"
                if worker != st["worker"]:
                    # a NEW incarnation: its executor state is fresh, so
                    # the seq stream restarts at 1 (the sequencer inits
                    # per-caller streams there). A same-worker re-resolve
                    # (controller restart) must keep the stream running.
                    st["seq"] = 0
                st["worker"] = worker
                st["tmpls"] = {}  # templates are per worker incarnation
                to_send = st["queue"]
                st["queue"] = []
                for s in to_send:
                    st["inflight"][s.task_id.binary()] = s
                    self._send_direct(worker, P.ACTOR_CALL,
                                      self._actor_call_msg(st, s))
        for s in to_fail:
            self._fail_actor_task_local(s, err)

    def _on_actor_update(self, aid: bytes, data: Any) -> None:
        """Actor liveness pubsub: flip the submitter state machine."""
        state = (data or {}).get("state")
        if state == "RESTARTING":
            to_fail: List[TaskSpec] = []
            need_resolve = False
            with self._actors_lock:
                st = self._actors.get(aid)
                if st is None or st["state"] == "DEAD":
                    return
                st["state"] = "RESOLVING"
                old_worker = st["worker"]
                st["worker"] = None
                # inflight calls may or may not have executed; resubmit only
                # those the user marked retriable (reference semantics:
                # max_task_retries>0 => at-least-once across restarts)
                retry = [s for s in st["inflight"].values()
                         if s.max_retries != 0]
                to_fail = [s for s in st["inflight"].values()
                           if s.max_retries == 0]
                st["inflight"] = {}
                st["queue"] = retry + st["queue"]
                need_resolve = True
            if old_worker is not None and self._reliable is not None:
                # calls in flight to the restarting incarnation are
                # resubmitted (or typed-failed) below: abandon their
                # retransmits to the old worker
                self._reliable.drop_target(old_worker)
            # the actor is NOT dead — calls that raced the restart and
            # are not retriable surface the typed "temporarily
            # unreachable" error (reference: ActorUnavailableError),
            # so callers can distinguish retry-me from gone-for-good
            from ray_tpu.exceptions import ActorUnavailableError
            for s in to_fail:
                self._fail_actor_task_local(
                    s, ActorUnavailableError(
                        ActorID(aid),
                        "actor restarting; call not retriable "
                        "(max_task_retries=0)"))
            if need_resolve:
                self._resolve_actor(aid)
        elif state == "DEAD":
            from ray_tpu.exceptions import ActorDiedError
            err = ActorDiedError(ActorID(aid), "actor died")
            with self._actors_lock:
                st = self._actors.get(aid)
                if st is None or st["state"] == "DEAD":
                    return
                st["state"] = "DEAD"
                st["error"] = err
                worker = st.get("worker")
                to_fail = st["queue"] + list(st["inflight"].values())
                st["queue"] = []
                st["inflight"] = {}
            if worker is not None and self._reliable is not None:
                # stop retransmitting queued calls into the dead actor's
                # worker — the local failure below is the recovery
                self._reliable.drop_target(worker)
            for s in to_fail:
                self._fail_actor_task_local(s, err)

    def _fail_actor_task_local(self, spec: TaskSpec, err) -> None:
        """The owner fails its own futures — and tells the controller,
        so tasks parked on these result objects fail fast with the
        actor's error instead of waiting on an object that will never
        exist (error propagation through the object graph)."""
        if spec.is_streaming:
            # streaming call: there are no static return objects — the
            # stream itself is the future to fail
            st = self._stream_for(spec.task_id.binary())
            if st is not None:
                st.fail(err)
                self._drop_stream(spec.task_id.binary())
            self._unpin_task_args(spec)
            return
        blob = P.dumps(err)
        results = []
        untracked = self.reference_counter._untracked
        for oid in spec.return_ids():
            b = oid.binary()
            meta = {"object_id": b, "error": blob}
            local = self._owner_local and b in untracked
            with self._meta_lock:
                self._meta[b] = meta
                if local:
                    # owner-local error object: nobody else can be parked
                    # on it (escape would have promoted it) — keep it out
                    # of the controller's directory. A later escape
                    # publishes the error meta like any owner-local value.
                    self._local_objects[b] = None
            self.memory_store.put(oid, _MetaReady(meta))
            if not local:
                results.append({"object_id": b})
        self._unpin_task_args(spec)
        try:
            self._send(P.TASK_DONE, {
                "task_id": spec.task_id.binary(),
                "trace": spec.trace,
                "results": results,
                "error": blob,
                "retriable": False,
                "owner": self.worker_id.binary(),
                "owner_notified": True,
                "is_actor_task": True,
                # sender is the OWNER, not the executing worker: the
                # controller must only record the error objects, never
                # run worker/lease bookkeeping against this identity
                "owner_report": True,
            })
        except Exception:
            pass

    def create_actor(self, spec: TaskSpec) -> None:
        spec.owner = self.worker_id
        if spec.trace is None:
            spec.trace = EV.child_trace(spec.task_id.hex())
        self.recorder.record_task(
            EV.SUBMITTED, spec.task_id.hex(), spec.trace,
            name=spec.name or spec.function.qualname, actor=True)
        self.request(P.CREATE_ACTOR, {"spec": spec})

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        tid_b = ref.id().task_id().binary()
        # direct actor call: in flight → cancel at the worker; still queued
        # client-side (address unresolved) → unqueue and fail locally (the
        # broker never saw the call, so CANCEL_TASK there would no-op and
        # the call would run anyway once the address arrived)
        worker = None
        queued_spec = None
        with self._actors_lock:
            for st in self._actors.values():
                if tid_b in st["inflight"]:
                    worker = st["worker"]
                    break
                for i, s in enumerate(st["queue"]):
                    if s.task_id.binary() == tid_b:
                        queued_spec = st["queue"].pop(i)
                        break
                if queued_spec is not None:
                    break
        if queued_spec is not None:
            from ray_tpu.exceptions import TaskCancelledError
            self._fail_actor_task_local(
                queued_spec, TaskCancelledError(queued_spec.task_id))
            return
        if worker is not None:
            self._send_direct(worker, P.CANCEL_QUEUED,
                              {"task_id": tid_b, "force": force})
            return
        # driver-leased direct task: cancel at its worker (the
        # controller never saw it); backlogged → unqueue + fail locally
        with self._lease_lock:
            direct_worker = self._direct_tids.get(tid_b)
            backlogged = None
            if direct_worker is None:
                for i, s in enumerate(self._direct_backlog):
                    if s.task_id.binary() == tid_b:
                        backlogged = s
                        del self._direct_backlog[i]
                        self._direct_backlog_bytes -= \
                            len(s.args_blob) + 512
                        break
        if backlogged is not None:
            from ray_tpu.exceptions import TaskCancelledError
            with self._inflight_lock:
                # never resubmit a cancelled task on RECONNECT
                self._inflight_specs.pop(tid_b, None)
            self._fail_actor_task_local(
                backlogged, TaskCancelledError(backlogged.task_id))
            return
        if direct_worker is not None:
            self._send_direct(direct_worker, P.CANCEL_QUEUED,
                              {"task_id": tid_b, "force": force})
            return
        self._send(P.CANCEL_TASK, {"task_id": tid_b, "force": force})

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._send(P.KILL_ACTOR, {"actor_id": actor_id.binary(),
                                  "no_restart": no_restart})

    # ------------------------------------------------------------ kv / pg
    def kv_put(self, key: bytes, value: bytes, ns: str = "",
               overwrite: bool = True) -> bool:
        return self.request(P.KV_OP, {"op": "put", "ns": ns, "key": key,
                                      "value": value, "overwrite": overwrite})["added"]

    def kv_get(self, key: bytes, ns: str = "") -> Optional[bytes]:
        return self.request(P.KV_OP, {"op": "get", "ns": ns, "key": key})["value"]

    def kv_del(self, key: bytes, ns: str = "") -> bool:
        return self.request(P.KV_OP, {"op": "del", "ns": ns, "key": key})["deleted"]

    def kv_exists(self, key: bytes, ns: str = "") -> bool:
        return self.request(P.KV_OP, {"op": "exists", "ns": ns, "key": key})["exists"]

    def kv_keys(self, prefix: bytes = b"", ns: str = "") -> List[bytes]:
        return self.request(P.KV_OP, {"op": "keys", "ns": ns, "prefix": prefix})["keys"]

    def state_query(self, what: str, **kw) -> Any:
        return self.request(P.STATE_QUERY, {"what": what, **kw})["rows"]

    # ----------------------------------------------------------- functions
    def export_function(self, key: str, blob: bytes) -> None:
        self.request(P.EXPORT_FUNCTION, {"key": key, "blob": blob})

    def fetch_function(self, key: str) -> Optional[bytes]:
        return self.request(P.FETCH_FUNCTION, {"key": key})["blob"]

    # ------------------------------------------------------------ timeline
    def _record_event(self, spec: TaskSpec, event: str) -> None:
        if not self.config.enable_timeline:
            return
        self._timeline_buf.append({
            "name": spec.name or spec.function.qualname, "cat": "task",
            "ph": "i", "ts": time.time() * 1e6, "pid": os.getpid(),
            "tid": threading.get_ident() % 1_000_000,
            "args": {"task_id": spec.task_id.hex(), "event": event}})
        if len(self._timeline_buf) >= 512:
            self.flush_timeline()

    def record_span(self, name: str, start_s: float, dur_s: float,
                    **args) -> None:
        self._timeline_buf.append({
            "name": name, "cat": "task", "ph": "X", "ts": start_s * 1e6,
            "dur": dur_s * 1e6, "pid": os.getpid(),
            "tid": threading.get_ident() % 1_000_000, "args": args})
        if len(self._timeline_buf) >= 512:
            self.flush_timeline()

    def flush_events(self) -> None:
        """Push buffered flight-recorder events to the controller now
        (state queries call this so fresh local events are visible)."""
        self.recorder.flush()

    def flush_timeline(self) -> None:
        if not self._timeline_buf:
            return
        buf, self._timeline_buf = self._timeline_buf, []
        try:
            self._send(P.TIMELINE_EVENTS, {"events": buf})
        except Exception:
            pass

    # ------------------------------------------------------------- pubsub
    def subscribe(self, channel: str, cb: Callable) -> None:
        self.pubsub_handlers.setdefault(channel, []).append(cb)
        self._send(P.SUBSCRIBE, {"channel": channel})

    def publish(self, channel: str, data: Any) -> None:
        self._send(P.PUBSUB, {"channel": channel, "data": data})


class _MetaReady:
    """Marker in the memory store: result meta arrived, value not yet
    materialized (lazy deserialization at first get)."""
    __slots__ = ("meta",)

    def __init__(self, meta: dict):
        self.meta = meta


def _weakref_targets(value, depth: int = 3) -> list:
    """Weakref-able objects inside ``value`` whose lifetime tracks the
    zero-copy buffers (numpy arrays and arbitrary user objects). Plain
    containers are walked shallowly; values with no weakref-able parts
    (pure bytes/str/scalars — which pickle COPIES out of the buffer
    anyway) return []."""
    out: list = []

    def walk(v, d):
        if d < 0:
            return
        tv = type(v)
        if tv in (int, float, str, bytes, bytearray, bool,
                  type(None)):
            return
        if tv is dict:
            for x in v.values():
                walk(x, d - 1)
            return
        if tv in (list, tuple, set, frozenset):
            for x in v:
                walk(x, d - 1)
            return
        try:
            import weakref
            weakref.ref(v)
        except TypeError:
            return
        out.append(v)

    walk(value, depth)
    return out
