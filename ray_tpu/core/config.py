"""Runtime configuration knobs.

Equivalent of the reference's ``RAY_CONFIG`` table
(``src/ray/common/ray_config_def.h``, 218 knobs): every knob has a typed
default and is overridable via an environment variable
``RAY_TPU_<NAME>`` or via the ``_system_config`` dict passed to
``ray_tpu.init``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(_ENV_PREFIX + name.upper())
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclass
class Config:
    # --- object store (reference: plasma defaults, ray_config_def.h) ---
    #: Objects at or below this size are passed inline in RPCs / stored in
    #: the in-process memory store (reference: max_direct_call_object_size).
    max_inline_object_size: int = 100 * 1024
    #: Fraction of system memory for the per-node shared-memory store.
    object_store_memory_fraction: float = 0.3
    #: Absolute cap (bytes) for the object store; 0 = derive from fraction.
    object_store_memory: int = 0
    #: Directory for spilled objects (reference: object_spilling_config).
    spill_dir: str = "/tmp/ray_tpu/spill"
    #: Start spilling when the store passes this fraction of capacity.
    object_spilling_threshold: float = 0.8
    #: Bytes of the store segment to prefault at startup (background).
    #: Faulted pages make first-touch puts memcpy-class. Deliberately small:
    #: populated tmpfs pages are committed RAM, and several node managers
    #: can share one host (cluster_utils tests) — large objects are instead
    #: prefaulted per-create, and recycled extents stay warm.
    object_store_prefault_bytes: int = 256 << 20
    #: Owner-local small objects (reference: the in-process memory store +
    #: owner-based object directory, core_worker's ownership model). When
    #: on, inline-sized objects (puts and task returns at or below
    #: max_inline_object_size) are tracked ONLY by their owner: no
    #: controller directory entry, no REF_DELTAS traffic, freed by owner
    #: GC. A ref that escapes (serialized, passed as a task arg) promotes
    #: the object to controller tracking and publishes its value so
    #: borrowers and dep-parked tasks resolve exactly as before.
    #: RAY_TPU_OWNER_LOCAL_OBJECTS=0 restores controller-tracked objects.
    owner_local_objects: bool = True

    # --- scheduler (reference: hybrid_scheduling_policy.h) ---
    #: Pack onto a node until its critical-resource utilization crosses this
    #: threshold, then spread (reference: scheduler_spread_threshold = 0.5).
    scheduler_spread_threshold: float = 0.5
    #: Top-k fraction of nodes considered for random choice among best
    #: (reference: scheduler_top_k_fraction).
    scheduler_top_k_fraction: float = 0.2
    scheduler_top_k_absolute: int = 1

    # --- health / heartbeats (reference: gcs_health_check_manager.h) ---
    health_check_period_ms: int = 1000
    #: grace before a delta-driven refcount zero actually frees the
    #: object: absorbs in-flight +1 deltas from other processes
    #: (cross-process batches have no global ordering)
    free_grace_s: float = 2.0
    #: how long a create blocks behind spilling/eviction before
    #: surfacing ObjectStoreFullError (plasma create-queue analog)
    store_full_timeout_s: float = 30.0
    health_check_timeout_ms: int = 10000
    #: Missed-heartbeat budget before a node is declared dead.
    health_check_failure_threshold: int = 5

    # --- tasks / retries ---
    #: Default max retries for normal tasks (reference: task max_retries=3).
    task_max_retries: int = 3
    #: Default max restarts for actors (0 = no restart).
    actor_max_restarts: int = 0
    #: Lease/worker reuse idle timeout (reference: idle_worker_killing).
    idle_worker_kill_s: float = 60.0
    #: Tasks kept in flight per leased worker (reference: pipelined lease
    #: reuse, direct_task_transport.h:157 OnWorkerIdle) — the worker
    #: executes serially from its local queue, so the lease holds ONE
    #: resource allocation regardless of depth.
    dispatch_pipeline_depth: int = 8
    #: workers to warm per node when a driver connects (reference:
    #: prestart_worker_first_driver); 0 disables
    prestart_workers: int = 2
    #: fork workers from a pre-imported zygote process (~ms per spawn)
    #: instead of cold interpreter boots (~seconds). The lever behind
    #: actor-burst throughput: every actor needs a fresh dedicated
    #: worker. RAY_TPU_WORKER_ZYGOTE=0 restores cold spawns.
    worker_zygote: bool = True
    #: Max workers a node will start per CPU if unspecified.
    workers_per_cpu: int = 1

    # --- transport ---
    #: Base directory for this session (sockets, logs, spill).
    session_dir: str = ""
    #: msgpack/pickle wire chunk size for large transfers.
    transfer_chunk_bytes: int = 8 * 1024 * 1024
    #: Pull-manager admission budget: total bytes of concurrently
    #: in-flight inbound object pulls (reference: pull_manager.h retry
    #: budget). At least one pull is always admitted.
    max_inflight_pull_bytes: int = 256 << 20
    #: Fail a pull (and report the stale location) after this long.
    pull_timeout_s: float = 60.0
    #: Source-side flow control: max unacked chunks per outbound stream.
    stream_window_chunks: int = 4

    # --- OOM defense (reference: memory_monitor.h:52 +
    # worker_killing_policy.h:34) ---
    #: Kill workers when node memory passes this fraction; <=0 disables.
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 500
    #: Consecutive breaches required before killing (debounces spikes).
    memory_monitor_breaches: int = 2
    #: OOM kills retry from their own budget (reference: task_oom_retries
    #: is separate from max_retries), with a delay so a saturated node
    #: gets time to clear before the task lands again.
    task_oom_retries: int = 15
    oom_retry_delay_s: float = 1.0

    # --- reliable delivery (core/reliable.py: ack/retransmit for the
    # critical one-way control messages; reference role: gRPC retries +
    # raylet lease/reconnect give the reference at-least-once RPCs) ---
    #: RAY_TPU_RELIABLE_DELIVERY=0 disables the sublayer (messages fall
    #: back to fire-and-forget; chaos drops of the critical set become
    #: designed-in hangs again).
    reliable_delivery: bool = True
    #: Retransmit backoff: equal-jitter exponential, base * 2^attempt
    #: capped. The base floor (base/2) must exceed the batched-ack RTT.
    retransmit_base_s: float = 0.25
    retransmit_cap_s: float = 5.0
    #: Give up (typed DeliveryFailedError via the on_fail hook) after
    #: this many transmissions without an ack or peer-death notice.
    #: Sized so a healed multi-second partition always recovers first.
    retransmit_max_attempts: int = 12
    #: Batched acks flush within this window (effectively piggybacking
    #: on traffic bursts without a per-message ack send).
    ack_flush_delay_s: float = 0.02
    #: Actor-side in-order admission: how long a call may wait for a
    #: missing predecessor (a dropped ACTOR_CALL being retransmitted)
    #: before the gap is skipped (reference:
    #: actor_scheduling_queue reorder wait). Sized to cover several
    #: retransmit backoff rounds; bounds delay, never hangs.
    actor_reorder_wait_s: float = 10.0

    # --- streaming generators (core ObjectRefGenerator; reference:
    # num_returns="streaming" + _generator_backpressure_num_objects) ---
    #: Consumer-paced credit window: a generator task pauses after this
    #: many yielded-but-unconsumed items until STREAM_CREDIT reports
    #: consumption (bounds the object store footprint of a fast
    #: producer). <= 0 disables backpressure. Per-call override via
    #: ``options(generator_backpressure_num_objects=...)``.
    generator_backpressure_num_objects: int = 64

    # --- MPMD pipeline (parallel/mpmd_pipeline.py) ---
    #: Seconds a pipeline stage's mailbox take may starve before the
    #: stage fails with a typed TimeoutError (a dead neighbor stage
    #: must surface as an error at the driver, never a hang). Sized
    #: well above any sane per-microbatch compute; shrink it in tests
    #: that provoke stalls. Per-pipeline override via
    #: ``MPMDPipeline(mailbox_deadline_s=...)``.
    pipeline_mailbox_deadline_s: float = 120.0

    # --- retries / fault tolerance hardening ---
    #: Lease/reconnect retry backoff: exponential with full jitter,
    #: base * 2^attempt capped at the cap (reference retry shape; the
    #: chaos harness forces many drivers to retry at once — full jitter
    #: de-correlates the herd). Replaces the historical fixed 2.0s sleep.
    lease_backoff_base_s: float = 0.5
    lease_backoff_cap_s: float = 10.0

    # --- dashboard / job REST (reference: dashboard/head.py) ---
    dashboard_enabled: bool = True
    #: 0 picks an ephemeral port; the chosen address is written to
    #: <session_dir>/dashboard.json.
    dashboard_port: int = 0
    #: Timeout for control-plane RPCs (s).
    rpc_timeout_s: float = 60.0

    # --- task events / observability ---
    task_events_report_interval_ms: int = 1000
    task_events_max_buffer: int = 100_000
    enable_timeline: bool = True
    #: Flight recorder (core/events.py): per-process bounded event ring
    #: flushed to the controller as TASK_EVENTS. Disable with
    #: RAY_TPU_ENABLE_TASK_EVENTS=0 (traces/timeline go dark; the task
    #: path loses its only per-hop observability).
    enable_task_events: bool = True
    #: Ring capacity per process; overflow drops the OLDEST events,
    #: counted in the runtime_events_dropped_total metric.
    task_events_ring_size: int = 4096

    # --- per-request tracing (serve/request_trace.py, serve/slo.py) ---
    #: Per-request span recording on the serve path. Disabling turns
    #: the request plane dark (waterfalls, /api/v0/requests,
    #: `ray-tpu trace` all empty); aggregate serve metrics keep working.
    enable_request_trace: bool = True
    #: Tail sampling: 1-in-N requests ship their spans to the
    #: controller even when fast and healthy (seeded per-router, so a
    #: fixed seed gives a deterministic sample). Slow (SLO-tripped),
    #: failed, and shed requests ALWAYS ship. 0 disables the baseline
    #: sample (only slow/failed/shed ship).
    trace_sample_n: int = 100
    #: Completed request traces retained at the controller (drop-oldest).
    request_trace_max: int = 512
    #: SLO budgets evaluated per phase by the serve/slo.py watchdog.
    #: Tripping any budget flips the request to always-ship and
    #: increments serve_slo_violations_total{phase}. <=0 disables that
    #: budget.
    slo_queue_s: float = 1.0
    slo_ttft_s: float = 5.0
    slo_inter_token_p99_s: float = 1.0

    # --- fleet metrics plane (core/metrics_plane.py) ---
    #: Per-process periodic METRIC_REPORT snapshots to the controller.
    #: RAY_TPU_ENABLE_METRICS_REPORT=0 turns the fleet plane dark
    #: (process-local /metrics endpoints keep working).
    enable_metrics_report: bool = True
    #: Reporter cadence per process (the fleet resolution floor).
    metrics_report_interval_ms: int = 1000
    #: Width of one time-series ring slot at the controller (rates and
    #: quantile windows are computed on this grid).
    metrics_ring_interval_s: float = 1.0
    #: Slots retained per (metric, labelset, origin) series — bounds
    #: the controller's memory (600 x 1s = 10 min of history).
    metrics_ring_slots: int = 600

    # --- TPU ---
    #: Name of the countable chip resource (reference:
    #: python/ray/_private/accelerators/tpu.py uses "TPU").
    tpu_resource_name: str = "TPU"
    #: Auto-create a `TPU-{pod_type}-head` resource on slice hosts
    #: (reference: tpu.py:379-382).
    tpu_pod_head_resource: bool = True

    extra: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        for f in fields(self):
            if f.name == "extra":
                continue
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    def apply_system_config(self, system_config: Dict[str, Any]) -> None:
        for key, value in (system_config or {}).items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self.extra[key] = value

    def to_json(self) -> str:
        d = {f.name: getattr(self, f.name) for f in fields(self) if f.name != "extra"}
        d.update(self.extra)
        return json.dumps(d)

    @classmethod
    def from_json(cls, raw: str) -> "Config":
        data = json.loads(raw)
        cfg = cls()
        cfg.apply_system_config(data)
        return cfg


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
