"""TPU accelerator detection and isolation.

Equivalent of the reference's ``python/ray/_private/accelerators/tpu.py``
(TPUAcceleratorManager :75): detect chips on this host, the pod type of the
slice this host belongs to, the host's worker index within the slice, and
per-task chip isolation via ``TPU_VISIBLE_CHIPS`` (:158-192). Detection is
env-var driven (GCE/GKE metadata endpoints are not reachable in all
environments; the same env vars the metadata would populate are honored):

- ``TPU_ACCELERATOR_TYPE`` / ``ACCELERATOR_TYPE`` — e.g. ``v5litepod-64``
- ``TPU_WORKER_ID`` — host index within the slice
- ``TPU_CHIPS_PER_HOST_BOUNDS`` / ``TPU_CHIPS`` — chips on this host
- ``TPU_NAME`` — pod/slice name

If jax is already imported (or ``RAY_TPU_DETECT_WITH_JAX=1``), chip count
falls back to ``jax.local_device_count()``.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

NUM_TPUS_PER_HOST_DEFAULT = 4


def tpu_chip_count() -> int:
    raw = os.environ.get("TPU_CHIPS")
    if raw:
        return int(raw)
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")  # e.g. "2,2,1"
    if bounds:
        n = 1
        for part in bounds.split(","):
            n *= int(part)
        return n
    if os.environ.get("TPU_ACCELERATOR_TYPE") or os.environ.get("ACCELERATOR_TYPE"):
        return NUM_TPUS_PER_HOST_DEFAULT
    jax = sys.modules.get("jax")
    if jax is not None or os.environ.get("RAY_TPU_DETECT_WITH_JAX") == "1":
        try:
            import jax
            return sum(1 for d in jax.local_devices() if d.platform == "tpu")
        except Exception:
            return 0
    return 0


def tpu_accelerator_type() -> Optional[str]:
    return os.environ.get("TPU_ACCELERATOR_TYPE") or os.environ.get("ACCELERATOR_TYPE")


def tpu_pod_type() -> Optional[str]:
    """Normalized pod type, e.g. ``v5litepod-64`` -> ``v5e-64`` (reference:
    _get_current_node_tpu_pod_type, tpu.py:199)."""
    acc = tpu_accelerator_type()
    if not acc:
        return None
    acc = acc.lower()
    for raw, norm in (("v5litepod", "v5e"), ("v5p", "v5p"), ("v6e", "v6e"),
                      ("v4", "v4"), ("v3", "v3"), ("v2", "v2")):
        if acc.startswith(raw):
            return acc.replace(raw, norm, 1)
    return acc


def tpu_worker_index() -> int:
    return int(os.environ.get("TPU_WORKER_ID", "0"))


def tpu_pod_name() -> Optional[str]:
    """Reference: ray.util.accelerators.tpu.get_current_pod_name (:7)."""
    return os.environ.get("TPU_NAME")


def tpu_pod_worker_count() -> int:
    """Total hosts in this slice (reference: get_current_pod_worker_count
    :19): chips(pod_type) / chips_per_host."""
    pod = tpu_pod_type()
    if not pod:
        return 1
    try:
        total_chips = int(pod.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 1
    per_host = max(1, tpu_chip_count() or NUM_TPUS_PER_HOST_DEFAULT)
    return max(1, total_chips // per_host)


def set_visible_chips(chip_ids: List[int]) -> None:
    """Per-worker chip isolation (reference: tpu.py:158-192). Must run
    before jax initializes in the worker process."""
    os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(i) for i in chip_ids)
    # Bounds for a single-chip or sub-host topology.
    n = len(chip_ids)
    if n == 1:
        os.environ["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
        os.environ["TPU_PROCESS_BOUNDS"] = "1,1,1"


def gang_resource_name() -> Optional[str]:
    """`TPU-{pod_type}-head` (reference: tpu.py:379-382)."""
    pod = tpu_pod_type()
    return f"TPU-{pod}-head" if pod else None
