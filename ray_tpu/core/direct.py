"""Direct peer-to-peer channels between processes.

The reference pushes actor tasks and task results directly between core
workers over gRPC (``transport/direct_actor_task_submitter.h``,
``direct_actor_transport.h:51``) and moves object chunks directly between
object managers (``object_manager.h:206``) — only control metadata transits
the GCS. This module provides the equivalent substrate here: every process
(driver, worker, node manager) binds one ROUTER socket at a deterministic
address derived from its identity; peers that want to talk to it connect a
DEALER (identity = their own id) and push typed messages. Replies travel
over the *recipient's* outgoing DEALER to the original sender's ROUTER, so
each socket has exactly one owning thread (ROUTER: the pump/recv thread;
DEALERs: the flusher/send thread) — no cross-thread zmq use.

Addressing: ipc sockets under ``<session_dir>/direct/`` keyed by identity
hex. All processes of one cluster share the session directory (multi-node
tests run node managers on one host, like ``ray.cluster_utils.Cluster``);
a TCP registry can replace the derivation for true multi-host without
changing callers (they only use :func:`direct_addr`).
"""

from __future__ import annotations

import os


def direct_dir(session_dir: str) -> str:
    return os.path.join(session_dir, "direct")


def direct_addr(session_dir: str, ident: bytes) -> str:
    """Deterministic channel address for a peer identity (WorkerID / node
    identity bytes). Only an 8-byte prefix goes into the filename: unix
    socket paths cap at 107 chars and 64 random bits are ample within one
    session."""
    return f"ipc://{direct_dir(session_dir)}/{ident[:8].hex()}.sock"


def ensure_dir(session_dir: str) -> None:
    os.makedirs(direct_dir(session_dir), exist_ok=True)
