"""The controller: single control-plane authority for the cluster.

Equivalent of the reference's GCS server (``src/ray/gcs/gcs_server/
gcs_server.cc:138``) *plus* the scheduling half of the raylet
(``ClusterTaskManager`` / ``LocalTaskManager``): node membership, actor
directory, placement groups, KV store, function store, pubsub, health
checks, task-event sink, object directory, reference-count authority, and
task scheduling/dispatch. Collapsing GCS + raylet scheduling into one
authority removes the gossip/spillback machinery (``ray_syncer``,
``HandleRequestWorkerLease``) — consistent-by-construction scheduling, at
the cost of a single broker hop per message, which a TPU-pod-scale cluster
(tens of hosts, not thousands) tolerates.

Threading model: one event-loop thread owns the ROUTER socket (mirroring the
GCS's single asio io_context); cross-thread sends are marshaled through a
queue + wakeup. A background thread runs health checks.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

import zmq

from ray_tpu.core import chaos as CH
from ray_tpu.core import events as EV
from ray_tpu.core import protocol as P
from ray_tpu.core import reliable as RD
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from ray_tpu.core.reference_counter import GlobalRefTable
from ray_tpu.core.scheduler import ClusterResourceScheduler, NodeResources
from ray_tpu.core.task_spec import ActorInfo, PlacementGroupSpec, TaskSpec

logger = logging.getLogger(__name__)


@dataclass
class ObjectEntry:
    object_id: ObjectID
    owner: Optional[bytes] = None          # identity of owning process
    inline: Optional[bytes] = None         # small-object payload
    size: int = 0
    locations: Set[bytes] = field(default_factory=set)   # node_id binaries
    error: Optional[bytes] = None          # pickled exception
    lineage_task: Optional[TaskSpec] = None
    spillable: bool = True


@dataclass
class PendingTask:
    spec: TaskSpec
    state: str = "PENDING_DEPS"  # PENDING_DEPS | QUEUED | PENDING_TRANSFER | RUNNING
    node_id: Optional[NodeID] = None
    worker: Optional[bytes] = None
    retries_left: int = 0
    submitted_at: float = 0.0
    deps_remaining: Set[bytes] = field(default_factory=set)
    transfers_remaining: Set[bytes] = field(default_factory=set)
    #: Scheduling-class key (reference: SchedulingClass in task_spec.h —
    #: tasks with identical resource shapes share feasibility): tasks whose
    #: key failed to place in a drain are skipped wholesale, making the
    #: drain O(#shapes + #dispatched) instead of O(#queued).
    shape_key: Optional[tuple] = None
    #: OOM kills draw from their own budget (reference: task_oom_retries),
    #: not max_retries; -1 = uninitialized (filled from config on first use)
    oom_retries_left: int = -1


@dataclass
class Lease:
    """A worker leased to one scheduling class (reference: worker leases,
    ``direct_task_transport.h`` — ``OnWorkerIdle`` pipelines queued tasks of
    the same scheduling key onto an already-leased worker). The lease holds
    exactly one resource allocation; up to ``dispatch_pipeline_depth`` tasks
    ride it concurrently (executed serially worker-side)."""
    worker: bytes
    node_b: bytes
    shape_key: tuple
    resources: Dict[str, float]
    inflight: Set[bytes] = field(default_factory=set)
    #: worker is blocked in a ray.get inside a task: its cpu is released
    #: and the pipeline is not refilled until it unblocks
    blocked: bool = False


@dataclass
class NodeInfo:
    node_id: NodeID
    identity: bytes
    resources: NodeResources
    last_heartbeat: float = 0.0
    idle_workers: Deque[bytes] = field(default_factory=collections.deque)
    all_workers: Dict[bytes, dict] = field(default_factory=dict)  # identity -> info
    starting_workers: int = 0
    stats: dict = field(default_factory=dict)
    alive: bool = True


class Controller:
    def __init__(self, session_dir: str, config: Config):
        self.session_dir = session_dir
        self.config = config
        # seeded fault injection (chaos.py): None in production
        self._chaos = CH.maybe_injector("controller")
        self._chaos_dedup = CH.SeqDeduper() if self._chaos is not None \
            else None
        # flight recorder + aggregation sink (core/events.py): the
        # controller's own events ingest locally; every other process
        # flushes TASK_EVENTS batches here. Guarded by _events_lock —
        # ingest can fire from the reliable layer's retransmit thread.
        self._events_lock = threading.Lock()
        self.flight_events: List[dict] = []
        self.recorder = EV.make_recorder("controller", config,
                                         send=self._ingest_events)
        # fleet metrics plane (core/metrics_plane.py): every process's
        # METRIC_REPORT snapshots merge here into bounded time-series
        # rings; the controller's own registry self-ingests through the
        # same path (MetricsPlane is internally locked — ingest fires
        # from the loop thread AND the health thread, the dashboard's
        # HTTP threads query).
        from ray_tpu.core.metrics_plane import MetricsPlane
        from ray_tpu.util import metrics as MX
        self.metrics_plane = MetricsPlane.from_config(config)
        # per-request trace store (serve/request_trace.py): replicas /
        # routers ship tail-sampled REQUEST_SPANS batches here.
        # Internally locked like the metrics plane — the dashboard's
        # HTTP threads read it directly.
        from ray_tpu.serve.request_trace import RequestTraceStore
        self.request_traces = RequestTraceStore(
            max_requests=getattr(config, "request_trace_max", 512))
        self.metrics_reporter = MX.make_reporter(
            self.metrics_plane.ingest,
            {"node": "head", "pid": os.getpid(), "role": "controller"},
            config)
        # reliable-delivery sublayer: TASK_DISPATCH/TASK_ASSIGN/
        # TASK_RESULT to workers, nodes and owners get ack/retransmit;
        # resends re-enter _send (thread-safe cross-thread marshal)
        self._reliable = RD.maybe_transport(
            config, lambda t, mt, pl: self._send(t, mt, pl),
            lambda route, pl: self._send(route, P.MSG_ACK, pl),
            rng=self._chaos.rng_for("retransmit")
            if self._chaos is not None else None, name="controller",
            recorder=self.recorder)
        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.ROUTER)
        self.sock.setsockopt(zmq.ROUTER_MANDATORY, 0)
        self.sock.setsockopt(zmq.LINGER, 0)
        # unbounded per-peer queues: result bursts (thousands of TASK_RESULT
        # pushes to one owner) must not be silently dropped at the HWM
        self.sock.setsockopt(zmq.SNDHWM, 0)
        self.sock.setsockopt(zmq.RCVHWM, 0)
        self.addr = P.socket_path(session_dir)
        self.sock.bind(self.addr)
        # wakeup channel for cross-thread sends
        self._wake_recv = self.ctx.socket(zmq.PULL)
        self._wake_recv.bind(f"inproc://ctl-wake-{id(self)}")
        self._wake_send = self.ctx.socket(zmq.PUSH)
        self._wake_send.connect(f"inproc://ctl-wake-{id(self)}")
        self._send_q: Deque[Tuple[bytes, bytes, bytes]] = collections.deque()
        self._call_q: Deque = collections.deque()  # marshaled loop calls
        self._send_lock = threading.Lock()
        self._sched_dirty = True
        # local_waiters parked on UNKNOWN objects: first-park timestamp
        # + audit strike counts (directory-hole detection)
        self._waiter_since: Dict[bytes, float] = {}
        self._hole_strikes: Dict[bytes, int] = {}
        # owner-local objects a borrower is parked on: object_id ->
        # owner identity we asked to publish (FETCH_OBJECT). Resolved by
        # the owner's PUT_OBJECT; audited against owner death.
        self._owner_fetches: Dict[bytes, bytes] = {}
        # rid -> (Event, slot) for in-flight worker profile requests
        # (dashboard HTTP threads wait; _h_profile_result fulfills)
        self._profile_waiters: Dict[bytes, tuple] = {}
        # last spawn-ahead pass for queued actor creations (rate limit)
        self._last_actor_prestart = 0.0
        # worker -> last runtime-env key (env-affinity dispatch)
        self._worker_env: Dict[bytes, str] = {}
        # worker identity -> owning driver identity: workers leased to a
        # driver for DIRECT task submission (reference: worker leases,
        # direct_task_transport.h — tasks bypass the controller wholly;
        # TASK_DONE only records results)
        self.driver_leases: Dict[bytes, bytes] = {}
        self._lease_node: Dict[bytes, bytes] = {}  # leased worker -> node
        self._pending_leases: List[tuple] = []  # [(driver, count_still_wanted)]
        self._lease_blocked: set = set()  # driver-leased workers in ray.get
        # reclaimed-while-blocked workers parked until NOTIFY_UNBLOCKED
        self._blocked_orphans: set = set()
        # per-peer outbox for loop-thread sends: flushed once per event-loop
        # cycle as MSG_BATCH frames — amortizes pickling + syscalls over a
        # burst without adding latency (flush happens before the next poll)
        self._outbox: Dict[bytes, List[Tuple[bytes, Any]]] = {}

        self.scheduler = ClusterResourceScheduler()
        self.refs = GlobalRefTable(self._queue_refcount_zero)
        #: delta-driven zero events park here for a grace window before
        #: the actual free: cross-process delta batches can zero the
        #: aggregate transiently while a direct-path consumer's pin
        #: (+1) is still in flight — freeing immediately loses the only
        #: copy of an object a queued task still needs. Owner-initiated
        #: frees (_h_owner_free) stay immediate: the owner's count is
        #: authoritative (reference: frees are owner-driven,
        #: reference_count.h).
        self._pending_frees: Dict[bytes, float] = {}

        self.peers: Dict[bytes, dict] = {}          # identity -> {kind, node_id}
        self.nodes: Dict[bytes, NodeInfo] = {}      # node_id binary -> NodeInfo
        self.objects: Dict[bytes, ObjectEntry] = {}
        self.actors: Dict[bytes, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}
        # callers long-polling for an actor's worker address (direct calls)
        self.actor_addr_waiters: Dict[bytes, List[Tuple[bytes, bytes]]] = \
            collections.defaultdict(list)
        self.actor_queues: Dict[bytes, Deque[Tuple[bytes, TaskSpec]]] = {}
        self.actor_workers: Dict[bytes, bytes] = {}   # actor_id -> worker identity
        self.worker_actors: Dict[bytes, bytes] = {}   # worker identity -> actor_id
        self.kv: Dict[str, Dict[bytes, bytes]] = collections.defaultdict(dict)
        self.functions: Dict[str, bytes] = {}
        self.pgs: Dict[bytes, PlacementGroupSpec] = {}
        self.pg_states: Dict[bytes, str] = {}
        self.pg_creators: Dict[bytes, bytes] = {}  # pg_id -> creator identity
        self.pending_pgs: Deque[Tuple[bytes, PlacementGroupSpec]] = collections.deque()
        self.subs: Dict[str, Set[bytes]] = collections.defaultdict(set)

        self.tasks: Dict[bytes, PendingTask] = {}    # task_id -> PendingTask
        # ready tasks grouped by scheduling class; dict preserves insertion
        # order so classes are drained round-robin-by-arrival
        self.ready_queues: Dict[tuple, Deque[bytes]] = {}
        self.leases: Dict[bytes, Lease] = {}          # worker identity -> lease
        self.class_leases: Dict[tuple, Set[bytes]] = collections.defaultdict(set)
        self.dep_waiters: Dict[bytes, Set[bytes]] = collections.defaultdict(set)   # object -> task_ids
        self.local_waiters: Dict[bytes, List[Tuple[bytes, bytes]]] = collections.defaultdict(list)  # object -> [(identity, rid)]
        self.task_table: Dict[bytes, dict] = {}       # state-API rows
        self.task_events: List[dict] = []
        self.jobs: Dict[bytes, dict] = {}
        self._job_counter = 0

        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._transfers: Dict[Tuple[bytes, bytes], int] = {}  # (object, dest_node) -> attempt

        # durable state (reference: gcs store client + redis tables);
        # everything not recovered here is re-announced via RECONNECT
        from ray_tpu.core.persistence import ControllerStore
        self.store = ControllerStore(session_dir)
        #: incarnation id: peers re-announce AT MOST ONCE per controller
        #: generation (a second RECONNECT for the same generation must not
        #: double-apply absolute refcounts or resubmit tasks twice)
        self.generation = os.urandom(8)
        self._reconnect_sent: Dict[bytes, float] = {}
        #: worker re-registrations that raced ahead of their node's
        #: re-registration; replayed when the node arrives
        self._orphan_workers: Dict[bytes, List[Tuple[bytes, dict]]] = \
            collections.defaultdict(list)
        self._started_at = time.monotonic()
        self._recovered_actors: Set[bytes] = set()
        self._recover()

    # ------------------------------------------------- durable state
    def _durable_state(self) -> dict:
        return {
            "kv": {ns: dict(d) for ns, d in self.kv.items()},
            "functions": dict(self.functions),
            "named_actors": [
                (info.namespace, info.name, info.spec)
                for aid, info in self.actors.items()
                if info.name and info.state != "DEAD"],
            "job_counter": self._job_counter,
        }

    def _recover(self) -> None:
        snap, ops = self.store.load()
        state = snap or {"kv": {}, "functions": {},
                         "named_actors": [], "job_counter": 0}
        for ns, d in state["kv"].items():
            self.kv[ns].update(d)
        self.functions.update(state["functions"])
        self._job_counter = state["job_counter"]
        named = {(ns, name): spec
                 for ns, name, spec in state["named_actors"]}
        for op in ops:
            kind = op[0]
            if kind == "kv_put":
                self.kv[op[1]][op[2]] = op[3]
            elif kind == "kv_del":
                self.kv[op[1]].pop(op[2], None)
            elif kind == "fn":
                self.functions[op[1]] = op[2]
            elif kind == "actor":
                spec = op[1]
                named[(spec.namespace, spec.actor_name)] = spec
            elif kind == "actor_dead":
                named = {k: s for k, s in named.items()
                         if s.actor_id.binary() != op[1]}
            elif kind == "job_counter":
                self._job_counter = max(self._job_counter, op[1])
        for (ns, name), spec in named.items():
            aid = spec.actor_id.binary()
            # RESTARTING until the hosting worker re-announces itself
            # (or the health loop's grace window expires it)
            self.actors[aid] = ActorInfo(
                actor_id=spec.actor_id, spec=spec, state="RESTARTING",
                name=name, namespace=ns)
            self.named_actors[(ns, name)] = aid
            self.actor_queues.setdefault(aid, collections.deque())
            self._recovered_actors.add(aid)
        if snap is not None or ops:
            logger.info(
                "controller: recovered %d kv namespaces, %d functions, "
                "%d named actors", len(self.kv), len(self.functions),
                len(named))

    # ------------------------------------------------------------------ run
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="controller", daemon=True)
        self._thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="controller-health", daemon=True)
        self._health_thread.start()

    def stop(self) -> None:
        self._shutdown.set()
        if self._reliable is not None:
            self._reliable.stop()
        with self._send_lock:
            pass
        try:
            self._wake_send.send(b"")
        except Exception:
            pass
        if self._thread:
            self._thread.join(timeout=5)
        self.store.close()

    def _run(self) -> None:
        poller = zmq.Poller()
        poller.register(self.sock, zmq.POLLIN)
        poller.register(self._wake_recv, zmq.POLLIN)
        while not self._shutdown.is_set():
            try:
                events = dict(poller.poll(timeout=1000))
            except zmq.ZMQError:
                break
            if self._wake_recv in events:
                while True:
                    try:
                        self._wake_recv.recv(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
            self._drain_sends()
            self._drain_calls()
            if self.sock in events:
                for _ in range(1000):
                    try:
                        frames = self.sock.recv_multipart(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
                    try:
                        self._handle(frames)
                    except Exception:
                        logger.exception("controller: error handling %s",
                                         frames[1] if len(frames) > 1 else frames)
            self._flush_outbox()
            self._drain_sends()
            # latency bound on the controller's OWN flight-recorder
            # events reaching the aggregation buffer
            self.recorder.maybe_flush()
        try:
            self.sock.close(0)
            self._wake_recv.close(0)
            self._wake_send.close(0)
        except Exception:
            pass

    def call_on_loop(self, fn, timeout: float = 10.0):
        """Run ``fn()`` on the controller loop thread and return its
        result. All controller state is owned by that single thread
        (mirroring the GCS's one io_context) — cross-thread readers like
        the dashboard must marshal through here rather than iterate live
        dicts."""
        if threading.current_thread() is self._thread:
            return fn()
        done = threading.Event()
        box: list = [None, None]

        def run():
            try:
                box[0] = fn()
            except BaseException as e:  # noqa: BLE001
                box[1] = e
            done.set()

        with self._send_lock:
            self._call_q.append(run)
        try:
            self._wake_send.send(b"", zmq.NOBLOCK)
        except zmq.ZMQError:
            pass
        if not done.wait(timeout):
            raise TimeoutError("controller loop busy")
        if box[1] is not None:
            raise box[1]
        return box[0]

    def _drain_calls(self) -> None:
        while self._call_q:
            try:
                run = self._call_q.popleft()
            except IndexError:
                break
            try:
                run()
            except Exception:
                logger.exception("controller: error in marshaled call")

    def _send(self, identity: bytes, mtype: bytes, payload: Any) -> None:
        """Thread-safe send. Loop-thread sends are buffered per peer and
        flushed at the end of the handling cycle (order-preserving);
        cross-thread sends are marshaled through the wake channel."""
        if self._reliable is not None:
            # stamp + ring-record critical one-way messages before the
            # chaos filter (a dropped message must already be tracked);
            # retransmitted payloads pass through untouched
            payload = self._reliable.stamp(identity, mtype, payload)
        if self._chaos is not None:
            for delay_s, pl in self._chaos.plan_send(
                    identity, mtype, payload):
                if delay_s > 0.0:
                    # the timer thread re-enters via the cross-thread
                    # marshal path, which is safe from any thread
                    t = threading.Timer(delay_s, self._send_now,
                                        args=(identity, mtype, pl))
                    t.daemon = True
                    t.start()
                else:
                    self._send_now(identity, mtype, pl)
            return
        self._send_now(identity, mtype, payload)

    def _send_now(self, identity: bytes, mtype: bytes, payload: Any) -> None:
        if threading.current_thread() is self._thread:
            box = self._outbox.get(identity)
            if box is None:
                box = self._outbox[identity] = []
            box.append((mtype, payload))
        else:
            blob = P.dumps(payload)
            with self._send_lock:
                self._send_q.append((identity, mtype, blob))
            try:
                self._wake_send.send(b"", zmq.NOBLOCK)
            except zmq.ZMQError:
                pass

    def _flush_outbox(self) -> None:
        if not self._outbox:
            return
        outbox, self._outbox = self._outbox, {}
        for identity, msgs in outbox.items():
            try:
                if len(msgs) == 1:
                    mtype, payload = msgs[0]
                    self.sock.send_multipart(
                        [identity, mtype, P.dumps(payload)], zmq.NOBLOCK)
                else:
                    self.sock.send_multipart(
                        [identity, P.MSG_BATCH, P.dumps({"msgs": msgs})],
                        zmq.NOBLOCK)
            except zmq.ZMQError:
                logger.warning("controller: drop %d msgs to %s", len(msgs),
                               identity.hex()[:8])

    def _drain_sends(self) -> None:
        while True:
            with self._send_lock:
                if not self._send_q:
                    return
                identity, mtype, blob = self._send_q.popleft()
            try:
                self.sock.send_multipart([identity, mtype, blob], zmq.NOBLOCK)
            except zmq.ZMQError:
                pass

    def _reply(self, identity: bytes, rid: bytes, data: Any, ok: bool = True) -> None:
        self._send(identity, P.GENERIC_REPLY if ok else P.ERROR_REPLY,
                   {"rid": rid, "data": data})

    # ------------------------------------------------------------- dispatch
    def _handle(self, frames: List[bytes]) -> None:
        identity, mtype, payload = frames[0], frames[1], P.loads(frames[2])
        if mtype == P.MSG_BATCH:
            for sub_type, sub_payload in payload["msgs"]:
                try:
                    self._dispatch_msg(identity, sub_type, sub_payload)
                except Exception:
                    logger.exception("controller: error in batched %s",
                                     sub_type)
            return
        self._dispatch_msg(identity, mtype, payload)

    def _dispatch_msg(self, identity: bytes, mtype: bytes, payload: Any) -> None:
        if self._chaos_dedup is not None and CH.check_dedup(
                self._chaos_dedup, payload):
            return  # injected duplicate of a message already handled
        if self._reliable is not None and \
                self._reliable.on_receive(identity, payload):
            return  # retransmit duplicate of a handled message
        if identity not in self.peers and mtype != P.REGISTER:
            # a peer from before a controller restart: process its message
            # (handlers tolerate unknown senders) and ask it to re-announce
            # itself (reference: raylet reconnect, node_manager.cc:1114)
            now = time.monotonic()
            if now - self._reconnect_sent.get(identity, 0.0) > 2.0:
                self._reconnect_sent[identity] = now
                self._send(identity, P.RECONNECT, {"gen": self.generation})
        handler = self._HANDLERS.get(mtype)
        if handler is None:
            logger.warning("controller: unknown message %s", mtype)
            return
        handler(self, identity, payload)

    # -------------------------------------------------------- registration
    def _h_register(self, identity: bytes, m: dict) -> None:
        kind = m["kind"]
        self._sched_dirty = True  # new node/worker = new capacity
        self.peers[identity] = {"kind": kind, "node_id": m.get("node_id"),
                                "pid": m.get("pid")}
        if kind == "node":
            node_id = NodeID(m["node_id"])
            existing = self.nodes.get(node_id.binary())
            if existing is not None and existing.identity == identity:
                # re-registration after a controller restart: keep the
                # NodeInfo we may have partially rebuilt
                existing.last_heartbeat = time.monotonic()
                info = existing
            else:
                res = NodeResources(node_id, m["resources"],
                                    m.get("labels") or {})
                info = NodeInfo(node_id=node_id, identity=identity,
                                resources=res,
                                last_heartbeat=time.monotonic())
                self.nodes[node_id.binary()] = info
                self.scheduler.add_node(res)
                self._publish("node", {"event": "added",
                                       "node_id": m["node_id"],
                                       "resources": m["resources"]})
            # reconnect re-announce: objects the node's store still holds
            # repopulate the object directory (reference: raylet reconnect
            # resends its object table, node_manager.cc:1114)
            for b, size in m.get("objects") or []:
                e = self._entry(b)
                e.locations.add(node_id.binary())
                e.size = e.size or size
                # wake anything already parked on this object (resubmitted
                # tasks in dep_waiters, blocked gets in local_waiters)
                self._object_created(b)
            # replay worker registrations that raced ahead of this node's
            for wid, wm in self._orphan_workers.pop(node_id.binary(), []):
                self._h_register(wid, wm)
        elif kind == "worker":
            nid = m["node_id"]
            node = self.nodes.get(nid)
            if node is None:
                # its node's (re-)registration hasn't arrived yet — stash,
                # else the worker is lost from the pool forever
                self._orphan_workers[nid].append((identity, m))
                return
            if not node.alive:
                # a worker of a DEAD node re-announcing in its death
                # throes (a RECONNECT races the node teardown — seen
                # when a drained slice's hosts get the proactive death
                # notice ~1s before their processes exit): admitting it
                # — especially _restore_actor_binding below — would
                # resurrect an actor onto a walking-dead worker whose
                # death nobody will ever report again, and callers
                # would retarget it forever
                return
            if identity not in node.all_workers:
                node.all_workers[identity] = {"pid": m.get("pid"),
                                              "worker_id": m.get("id")}
                node.starting_workers = max(0, node.starting_workers - 1)
                if m.get("actor_id") is None and not m.get("busy"):
                    # mid-task workers return to the idle pool at their
                    # TASK_DONE (transient resource over-admission until
                    # then self-corrects)
                    node.idle_workers.append(identity)
                    self._grant_parked_leases()
                    self._drain_waiting_tasks(node)
            if m.get("actor_id") is not None:
                self._restore_actor_binding(m["actor_id"], identity,
                                            m.get("node_id"))
        elif kind == "driver":
            if m.get("job_id"):
                # reconnecting driver keeps its job identity
                job_id = JobID(m["job_id"])
                self.jobs.setdefault(job_id.binary(), {
                    "job_id": job_id.hex(), "pid": m.get("pid"),
                    "start_time": time.time(), "status": "RUNNING"})
            else:
                self._job_counter += 1
                self.store.append(("job_counter", self._job_counter))
                job_id = JobID.from_int(self._job_counter)
                self.jobs[job_id.binary()] = {
                    "job_id": job_id.hex(), "pid": m.get("pid"),
                    "start_time": time.time(), "status": "RUNNING"}
            self.peers[identity]["job_id"] = job_id.binary()
            self._send(identity, P.REGISTER_REPLY, {
                "job_id": job_id.binary(),
                "head_node_id": next(iter(self.nodes), b""),
                "session_dir": self.session_dir,
                "config": self.config.to_json(),
            })
            self._prestart_workers()
            self._maybe_schedule()
            return
        self._send(identity, P.REGISTER_REPLY, {"ok": True,
                                                "config": self.config.to_json()})
        self._maybe_schedule()

    def _restore_actor_binding(self, aid: bytes, worker: bytes,
                               node_b: Optional[bytes]) -> None:
        """A surviving actor worker re-announced itself after a controller
        restart: rebind the actor to its worker and flip it ALIVE."""
        self.actor_workers[aid] = worker
        self.worker_actors[worker] = aid
        self._recovered_actors.discard(aid)
        info = self.actors.get(aid)
        if info is None or info.state == "ALIVE":
            return
        info.state = "ALIVE"
        if node_b is not None:
            info.node_id = NodeID(node_b)
            info.worker_id = WorkerID(worker) \
                if len(worker) == WorkerID.SIZE else None
            if info.spec is not None and info.spec.hold_resources:
                # the live actor still occupies its resources; the node's
                # fresh registration reset availability, so re-take them
                self.scheduler.force_acquire(
                    NodeID(node_b), self._sched_res(info.spec))
        self._publish(f"actor:{info.actor_id.hex()}",
                      {"state": "ALIVE", "actor_id": aid})
        self._answer_actor_addr_waiters(aid)

    # ------------------------------------------------------------- objects
    def _entry(self, object_id_b: bytes) -> ObjectEntry:
        e = self.objects.get(object_id_b)
        if e is None:
            e = ObjectEntry(ObjectID(object_id_b))
            self.objects[object_id_b] = e
        return e

    def _h_put_object(self, identity: bytes, m: dict) -> None:
        e = self._entry(m["object_id"])
        e.owner = e.owner or identity
        if m.get("inline") is not None:
            e.inline = m["inline"]
            e.size = len(e.inline)
        if m.get("node_id"):
            e.locations.add(m["node_id"])
            e.size = m.get("size", e.size)
            # transfer (if any) completed: allow future re-pulls to this
            # node after it frees its copy
            self._transfers.pop((m["object_id"], m["node_id"]), None)
        if m.get("error") is not None:
            e.error = m["error"]
        self._object_created(m["object_id"])
        if m.get("rid"):
            self._reply(identity, m["rid"], {"ok": True})

    def _object_created(self, object_id_b: bytes) -> None:
        """Wake tasks waiting on this object + local-waiters now satisfiable."""
        e = self.objects.get(object_id_b)
        for task_id in list(self.dep_waiters.pop(object_id_b, ())):
            t = self.tasks.get(task_id)
            if t is None:
                continue
            t.deps_remaining.discard(object_id_b)
            if t.state == "PENDING_DEPS" and not t.deps_remaining:
                self._enqueue_ready(task_id, t)
            elif t.state == "PENDING_TRANSFER":
                t.transfers_remaining.discard(object_id_b)
                if not t.transfers_remaining:
                    self._dispatch(task_id)
        self._waiter_since.pop(object_id_b, None)
        self._hole_strikes.pop(object_id_b, None)
        self._owner_fetches.pop(object_id_b, None)
        waiters = self.local_waiters.pop(object_id_b, [])
        for identity, rid in waiters:
            self._answer_location(identity, rid, object_id_b)
        self._maybe_schedule()

    def _h_get_location(self, identity: bytes, m: dict) -> None:
        object_id_b = m["object_id"]
        e = self.objects.get(object_id_b)
        if e is not None and (e.inline is not None or e.error is not None or e.locations):
            self._answer_location(identity, m["rid"], object_id_b,
                                  want_node=m.get("want_node"))
        else:
            # not created yet (or lost) — try lineage reconstruction, else
            # wait (the audit probes node stores for long-parked waiters:
            # probing here would broadcast on every ordinary
            # get-before-producer-finishes, the hot borrower path)
            if e is not None and e.lineage_task is not None and not e.locations \
                    and e.inline is None and e.error is None:
                self._reconstruct(e)
            elif e is None and object_id_b not in self._waiter_since:
                self._waiter_since[object_id_b] = time.monotonic()
            owner_b = m.get("owner")
            if owner_b and owner_b != identity and e is None \
                    and object_id_b not in self._owner_fetches:
                # owner-local object (never published): ask the owner to
                # publish its value; the PUT_OBJECT it sends resolves
                # this waiter through _object_created
                self._owner_fetches[object_id_b] = owner_b
                self._send(owner_b, P.FETCH_OBJECT,
                           {"object_id": object_id_b})
            self.local_waiters[object_id_b].append((identity, m["rid"]))

    def _answer_location(self, identity: bytes, rid: bytes, object_id_b: bytes,
                         want_node: Optional[bytes] = None) -> None:
        e = self.objects.get(object_id_b)
        if e is None:
            # raced with a release: answering with an error beats the
            # KeyError that used to swallow the reply and hang the get
            from ray_tpu.exceptions import ObjectLostError
            self._reply(identity, rid, {"error": P.dumps(
                ObjectLostError(ObjectID(object_id_b),
                                "freed before the location lookup"))})
            return
        if e.error is not None:
            self._reply(identity, rid, {"error": e.error})
            return
        if e.inline is not None:
            self._reply(identity, rid, {"inline": e.inline})
            return
        peer = self.peers.get(identity, {})
        want_node = want_node or peer.get("node_id")
        if want_node and want_node not in e.locations and e.locations:
            self._start_transfer(object_id_b, want_node)
            self.local_waiters[object_id_b].append((identity, rid))
            return
        if not e.locations:
            if e.lineage_task is not None:
                self._reconstruct(e)
                self.local_waiters[object_id_b].append((identity, rid))
                return
            from ray_tpu.exceptions import ObjectLostError
            self._reply(identity, rid,
                        {"error": P.dumps(ObjectLostError(e.object_id))})
            return
        self._reply(identity, rid, {"node_id": next(iter(e.locations)),
                                    "size": e.size})

    def _start_transfer(self, object_id_b: bytes, dest_node: bytes) -> None:
        """Ask the destination node to pull the object from a holder.
        The controller hands out the source address ONLY — the bytes move
        node-to-node over the direct channel (reference: the pull manager
        lives on the receiving object manager, pull_manager.h:52, and
        chunks never transit the GCS)."""
        self._begin_transfer(object_id_b, dest_node, attempt=1)

    def _begin_transfer(self, object_id_b: bytes, dest_node: bytes,
                        attempt: int) -> None:
        key = (object_id_b, dest_node)
        if key in self._transfers:
            return
        e = self.objects.get(object_id_b)
        if e is None or not e.locations:
            return
        src = next(iter(e.locations))
        src_node = self.nodes.get(src)
        dest = self.nodes.get(dest_node)
        if src_node is None or dest is None:
            return
        self._transfers[key] = attempt
        self._send(dest.identity, P.PULL_OBJECT, {
            "object_id": object_id_b, "src_identity": src_node.identity,
            "src_node": src, "size": e.size})

    def _h_pull_failed(self, identity: bytes, m: dict) -> None:
        """A destination node could not pull an object. If the SOURCE
        reported it missing (stale_src), drop that location; dest-local
        causes (timeout, store pressure) keep the holder. Retry from a
        holder up to a cap, then reconstruct via lineage or fail every
        waiter with ObjectLostError — never leave them hanging."""
        b = m["object_id"]
        e = self.objects.get(b)
        peer = self.peers.get(identity, {})
        dest_node = peer.get("node_id")
        attempts = 0
        if dest_node is not None:
            attempts = self._transfers.pop((b, dest_node), 0)
        if e is None:
            return
        src = m.get("src_node")
        if src is not None and m.get("stale_src"):
            e.locations.discard(src)
        if dest_node is None:
            return
        if e.locations and attempts < 5:
            self._begin_transfer(b, dest_node, attempts + 1)
        elif e.lineage_task is not None:
            self._reconstruct(e)
        else:
            self._fail_object_waiters(b, e)

    def _fail_object_waiters(self, b: bytes, e: ObjectEntry) -> None:
        from ray_tpu.exceptions import ObjectLostError
        err = P.dumps(ObjectLostError(e.object_id))
        for identity, rid in self.local_waiters.pop(b, []):
            self._reply(identity, rid, {"error": err})
        for tid in list(self.dep_waiters.pop(b, ())):
            self._handle_task_failure(
                tid, f"object {ObjectID(b).hex()[:12]} lost in transfer")

    def _h_ref_deltas(self, identity: bytes, m: dict) -> None:
        self.refs.apply_deltas(m["deltas"])

    def _h_lease_workers(self, identity: bytes, m: dict) -> None:
        """Grant idle workers to a driver for direct task submission.
        Each grant holds the worker's CPU until released/reclaimed.
        Under load there are no idle workers at request time, so the
        remainder is PARKED and granted as workers free up (pushed via
        LEASE_GRANT — the reference's lease requests queue in the
        raylet the same way)."""
        want = int(m.get("count", 1))
        granted = self._grant_leases(identity, want)
        self._reply(identity, m["rid"], {"workers": granted})
        remaining = want - len(granted)
        if remaining > 0:
            # one parked entry per driver (latest wins)
            self._pending_leases = [
                (d, n) for d, n in self._pending_leases if d != identity]
            self._pending_leases.append((identity, remaining))
            # multi-driver fairness: if another driver is hogging the
            # worker pool, rebalance toward this request now
            self._rebalance_leases()

    def _lease_quota(self) -> int:
        """Per-driver lease cap while several drivers want capacity.
        Measured rationale (perf multi_client phase): with one driver
        holding every CPU, the other drivers bounce between empty
        grants and the controller path, feeding the starvation
        reclaimer — aggregate throughput of 4 drivers fell BELOW one.
        An equal split keeps every driver on the direct path."""
        claimants = set(self.driver_leases.values())
        claimants.update(d for d, _ in self._pending_leases)
        n = max(1, len(claimants))
        # leasable capacity only: actor-dedicated workers can never be
        # granted, so counting them inflates the quota and lets one
        # driver hold every leasable worker without tripping rebalance
        capacity = sum(
            1 for node in self.nodes.values() if node.alive
            for w in node.all_workers if w not in self.worker_actors)
        # ceil: a floor quota would strand capacity % n workers idle
        # forever (every driver clamped below them)
        return max(1, -(-capacity // n))

    def _grant_leases(self, identity: bytes, want: int) -> List[bytes]:
        if self._pending_leases or len(
                set(self.driver_leases.values()) - {identity}) > 0:
            # other drivers hold or want leases: stay inside the quota
            have = sum(1 for d in self.driver_leases.values()
                       if d == identity)
            want = min(want, max(0, self._lease_quota() - have))
        granted: List[bytes] = []
        for node in self.nodes.values():
            if not node.alive:
                continue
            # never grant below the controller queue's own needs
            if node.stats.get("wait_worker"):
                continue
            while want > 0 and node.idle_workers:
                if not self.scheduler.try_acquire(
                        node.node_id, {"CPU": 1.0}):
                    break
                w = node.idle_workers.popleft()
                self.driver_leases[w] = identity
                self._lease_node[w] = node.node_id.binary()
                granted.append(w)
                want -= 1
            if want <= 0:
                break
        return granted

    def _grant_parked_leases(self) -> None:
        if not self._pending_leases:
            return
        if self.ready_queues:
            # queued controller-path tasks outrank parked lease
            # requests — granting here would re-take the CPU a
            # starvation reclaim just freed (revoke/grant thrash)
            return
        still: List[tuple] = []
        for driver, n in self._pending_leases:
            got = self._grant_leases(driver, n)
            if got:
                self._send(driver, P.LEASE_GRANT, {"workers": got})
            if len(got) < n:
                still.append((driver, n - len(got)))
        self._pending_leases = still

    def _rebalance_leases(self) -> None:
        """Revoke over-quota leases from hogging drivers so parked
        requests of under-quota drivers can be granted (reference: the
        raylet returns leased workers when other lease requests queue;
        here the quota makes the split explicit). Stable: only drivers
        ABOVE the quota lose leases, only down to the quota."""
        if not self._pending_leases:
            return
        quota = self._lease_quota()
        counts: Dict[bytes, int] = {}
        for d in self.driver_leases.values():
            counts[d] = counts.get(d, 0) + 1
        pending = {d for d, _ in self._pending_leases
                   if counts.get(d, 0) < quota}
        if not pending:
            return
        for w, d in list(self.driver_leases.items()):
            if counts.get(d, 0) <= quota:
                continue
            if w in self._lease_blocked:
                continue
            counts[d] -= 1
            self._send(d, P.LEASE_REVOKED, {"worker": w, "dead": False})
            self._reclaim_driver_lease(w)
        self._grant_parked_leases()

    def _h_release_leases(self, identity: bytes, m: dict) -> None:
        for w in m.get("workers", ()):
            self._reclaim_driver_lease(w)

    def _reclaimable_lease_for(self, demand, strategy) -> Optional[bytes]:
        """A driver-held lease whose reclaim could actually unblock
        ``demand``: its node must satisfy the demand once the lease's
        reserved {"CPU": 1.0} is returned. Returns None when no reclaim
        can help — demand needing resources no lease holds, demand
        requiring CPU the lease doesn't cover, PLACEMENT_GROUP tasks
        (their blocking condition is the bundle reservation, not node
        CPU), or node-pinned tasks whose pin excludes the lease's node."""
        if not demand.get("CPU"):
            # reclaiming frees only CPU; CPU-free demand can't benefit
            # (PG tasks reach here too: _sched_res gives them {})
            return None
        # group reclaimable leases by node: a multi-CPU demand may need
        # several reclaims (one per drain) on the same node to place, so
        # the test is "would freeing ALL this node's leases satisfy it"
        by_node: Dict[bytes, List[bytes]] = {}
        for w in self.driver_leases:
            if w in self._lease_blocked:
                continue
            node_b = self._lease_node.get(w)
            if node_b is not None:
                by_node.setdefault(node_b, []).append(w)
        for node_b, leases in by_node.items():
            node = self.scheduler.get_node(NodeID(node_b))
            if node is None or not node.alive or node.draining:
                continue
            if strategy.kind == "NODE_AFFINITY" and \
                    strategy.node_id is not None and \
                    strategy.node_id.binary() != node_b and \
                    not strategy.soft:
                continue
            if strategy.kind == "NODE_LABEL" and any(
                    node.labels.get(k) not in allowed
                    for k, allowed in strategy.hard_labels.items()):
                continue
            if all(node.available.get(k, 0.0)
                   + (float(len(leases)) if k == "CPU" else 0.0) + 1e-9
                   >= v for k, v in demand.items()):
                return leases[0]
        return None

    def _reclaim_driver_lease(self, worker: bytes) -> None:
        if self.driver_leases.pop(worker, None) is None:
            return
        node_b = self._lease_node.pop(worker, None)
        was_blocked = worker in self._lease_blocked
        self._lease_blocked.discard(worker)
        node = self.nodes.get(node_b) if node_b else None
        if node is not None and node.alive:
            if was_blocked:
                # serial thread is sitting in ray.get: idle-pooling it
                # now would bounce every dispatch (handback spin). Park
                # it; NOTIFY_UNBLOCKED returns it to the pool.
                self._blocked_orphans.add(worker)
                return
            self._release_res(NodeID(node_b), {"CPU": 1.0})
            if worker in node.all_workers:
                self._return_worker(worker)

    def _reclaim_driver_leases_of(self, driver: bytes) -> None:
        for w in [w for w, d in self.driver_leases.items() if d == driver]:
            self._reclaim_driver_lease(w)
        self._pending_leases = [
            (d, n) for d, n in self._pending_leases if d != driver]

    def _audit_driver_leases(self) -> None:
        """Reclaim leases (and parked lease requests) whose driver has
        gone silent — a crashed driver must not pin worker CPUs forever.
        Drivers ping every 2s; 30s of silence is decisive."""
        if not self.driver_leases and not self._pending_leases:
            return
        now = time.monotonic()
        drivers = set(self.driver_leases.values()) | {
            d for d, _ in self._pending_leases}
        for d in drivers:
            info = self.peers.get(d)
            last = (info or {}).get("last_seen")
            if info is None or (last is not None and now - last > 30.0):
                logger.warning(
                    "reclaiming worker leases of silent driver %s",
                    d.hex()[:8] if isinstance(d, bytes) else d)
                self._reclaim_driver_leases_of(d)

    def _h_owner_free(self, identity: bytes, m: dict) -> None:
        """The owner already evicted these never-shared extents from the
        segment (eager owner-side GC); drop metadata, waiters, and node
        bookkeeping. Node-side FREE_OBJECT is idempotent on an
        already-evicted extent."""
        for b in m["object_ids"]:
            if self.refs.force_release(b):
                self._on_refcount_zero(ObjectID(b))

    def _queue_refcount_zero(self, object_id: ObjectID) -> None:
        self._pending_frees[object_id.binary()] = \
            time.monotonic() + self.config.free_grace_s

    def _drain_pending_frees(self) -> None:
        """Health-loop: run frees whose grace expired and whose count
        did not resurrect meanwhile (a positive delta clears the
        tombstone, making is_released False)."""
        if not self._pending_frees:
            return
        now = time.monotonic()
        due = [b for b, t in self._pending_frees.items() if t <= now]
        for b in due:
            del self._pending_frees[b]
            if self.refs.is_released(b):
                self._on_refcount_zero(ObjectID(b))

    def _on_refcount_zero(self, object_id: ObjectID) -> None:
        b = object_id.binary()
        entry = self.objects.get(b)
        has_waiters = bool(self.dep_waiters.get(b)
                           or self.local_waiters.get(b))
        if has_waiters and entry is not None and (
                entry.inline is not None or entry.locations
                or entry.lineage_task is not None):
            # Someone is actively waiting AND the object is still
            # materializable: the zero is a transient artifact of delta
            # batching (the waiter holds a live ref whose +1 is still in
            # flight). Freeing now would strand the parked tasks — keep
            # the object; the pending +1 resurrects the count and a
            # later real zero retries the free.
            self.refs.cancel_release(b)
            return
        e = self.objects.pop(b, None)
        # Unrecoverable (no entry, or entry with no way to materialize):
        # fail the waiters loudly rather than stranding them.
        for tid in list(self.dep_waiters.pop(b, ())):
            self._handle_task_failure(
                tid, f"object {ObjectID(b).hex()[:12]} freed while the "
                f"task waited on it", retriable=False)
        waiters = self.local_waiters.pop(b, [])
        if waiters:
            from ray_tpu.exceptions import ObjectLostError
            err = P.dumps(ObjectLostError(object_id,
                                          "freed: refcount zero"))
            for identity, rid in waiters:
                self._reply(identity, rid, {"error": err})
        if e is None:
            return
        for node_b in e.locations:
            node = self.nodes.get(node_b)
            if node is not None:
                self._send(node.identity, P.FREE_OBJECT, {"object_id": b})

    # --------------------------------------------------------------- tasks
    def _h_submit_batch(self, identity: bytes, m: dict) -> None:
        """Pipelined submission: many specs in one message (reference:
        lease reuse + pipelined submission, direct_task_transport.h:157 —
        here the batching is at the wire layer). One schedule drain for the
        whole batch."""
        for spec in m["specs"]:
            self._h_submit_task(identity, {"spec": spec}, defer_schedule=True)
        self._maybe_schedule()

    def _h_submit_task(self, identity: bytes, m: dict,
                       defer_schedule: bool = False) -> None:
        spec: TaskSpec = m["spec"]
        if spec.is_actor_task:
            self._submit_actor_task(identity, spec)
            return
        # owner-side dependency seeding (see TaskSpec.arg_metas): fill
        # directory holes for args the owner already knows
        for b, am in (spec.arg_metas or {}).items():
            e = self.objects.get(b)
            if e is None or (e.inline is None and e.error is None
                             and not e.locations):
                e = self._entry(b)
                if am.get("inline") is not None:
                    e.inline = am["inline"]
                if am.get("node_id"):
                    e.locations.add(am["node_id"])
                e.size = e.size or am.get("size", 0)
                self._object_created(b)
        t = PendingTask(spec=spec, retries_left=spec.max_retries,
                        submitted_at=time.monotonic())
        tid = spec.task_id.binary()
        self.tasks[tid] = t
        self.task_table[tid] = {
            "task_id": spec.task_id.hex(), "name": spec.name or str(spec.function),
            "state": "PENDING_ARGS_AVAIL", "type": "ACTOR_CREATION_TASK"
            if spec.is_actor_creation else "NORMAL_TASK",
            "submitted_at": time.time(),
        }
        # phase 1: wait for all arg objects to exist somewhere
        for _, oid in spec.arg_refs:
            b = oid.binary()
            e = self.objects.get(b)
            if e is None or (e.inline is None and e.error is None and not e.locations):
                t.deps_remaining.add(b)
                self.dep_waiters[b].add(tid)
                if e is not None and e.lineage_task is not None:
                    self._reconstruct(e)
        if not t.deps_remaining:
            self._enqueue_ready(tid, t)
            if not defer_schedule:
                self._maybe_schedule()

    @staticmethod
    def _sched_res(spec: TaskSpec) -> Dict[str, float]:
        """Placement-group tasks consume pre-reserved bundle resources, not
        fresh node capacity (reference: bundle resources are renamed
        `CPU_group_<pgid>` instances; here the reservation itself is the
        accounting)."""
        if spec.scheduling_strategy.kind == "PLACEMENT_GROUP":
            return {}
        return spec.resources

    def _enqueue_ready(self, tid: bytes, t: PendingTask) -> None:
        """Mark a task ready and file it under its scheduling class."""
        t.state = "QUEUED"
        self._sched_dirty = True
        if t.shape_key is None:
            strat = t.spec.scheduling_strategy
            if t.spec.is_actor_creation:
                # never pipelined onto a shared lease (pins its worker)
                t.shape_key = (tid,)
            elif strat.kind in ("DEFAULT", "SPREAD"):
                t.shape_key = (strat.kind,
                               tuple(sorted(self._sched_res(t.spec).items())))
            else:
                # node-affinity / PG / label strategies are evaluated
                # per-task: give each its own class
                t.shape_key = (tid,)
        q = self.ready_queues.get(t.shape_key)
        if q is None:
            q = self.ready_queues[t.shape_key] = collections.deque()
        q.append(tid)

    def _lease_depth(self, key: Optional[tuple]) -> int:
        # SPREAD classes don't pipeline (piling tasks on one worker would
        # defeat the strategy); DEFAULT classes ride the full depth
        if key and key[0] == "SPREAD":
            return 1
        return max(1, self.config.dispatch_pipeline_depth)

    def _refill_lease(self, lease: Lease) -> None:
        """Pipeline tasks of the lease's scheduling class onto its worker up
        to the configured depth — no new resource acquisition, no pick_node
        (reference: OnWorkerIdle). The single refill path for every caller."""
        q = self.ready_queues.get(lease.shape_key)
        if not q or lease.blocked:
            return
        depth = self._lease_depth(lease.shape_key)
        while q and len(lease.inflight) < depth:
            tid = q.popleft()
            t = self.tasks.get(tid)
            if t is None or t.state != "QUEUED":
                continue
            self._dispatch_on_lease(lease, tid, t)

    def _fill_leases_for_class(self, key: tuple, q: Deque[bytes]) -> None:
        for w in list(self.class_leases.get(key, ())):
            if not q:
                return
            lease = self.leases.get(w)
            if lease is None:
                self.class_leases[key].discard(w)
                continue
            self._refill_lease(lease)

    def _release_res(self, node_id, resources) -> None:
        """Release node resources AND mark the scheduler dirty: freed
        capacity can admit queued work."""
        self.scheduler.release(node_id, resources)
        self._sched_dirty = True

    def _maybe_schedule(self, force: bool = False) -> None:
        """Drain the ready queues (reference:
        ClusterTaskManager::ScheduleAndDispatchTasks). A scheduling class
        that fails to place blocks only itself, and the drain costs
        O(#classes + #dispatched) — not O(#queued tasks).

        Event-driven: a no-op unless capacity or demand changed since the
        last drain (``_sched_dirty``). Lease pipelines refill inline at
        completion (_lease_housekeeping), so a full drain per TASK_DONE
        would re-scan every class x lease for nothing — measured at ~30%
        of controller CPU on the async-task hot path. The health loop
        forces a periodic drain as a self-healing backstop."""
        if not self._sched_dirty and not force:
            return
        self._sched_dirty = False
        self._prestart_for_actor_demand()
        if self.ready_queues:
            empties = []
            for key, q in self.ready_queues.items():
                self._fill_leases_for_class(key, q)
                while q:
                    tid = q[0]
                    t = self.tasks.get(tid)
                    if t is None or t.state != "QUEUED":
                        q.popleft()
                        continue
                    node_id = self.scheduler.pick_node(
                        self._sched_res(t.spec), t.spec.scheduling_strategy)
                    if node_id is None:
                        # driver-held worker leases can starve the queue
                        # (their CPU is reserved): reclaim one per drain —
                        # but only a lease whose freed CPU would make THIS
                        # demand placeable on its node. Demand infeasible
                        # for other reasons (e.g. a custom resource no
                        # node provides) must not dismantle the
                        # direct-transport lease pool one drain at a time.
                        # BLOCKED leases are exempt — their CPU is
                        # already released, and returning a worker whose
                        # serial thread sits in ray.get to the idle pool
                        # wedges the cluster in a dispatch/bounce loop.
                        w = self._reclaimable_lease_for(
                            self._sched_res(t.spec),
                            t.spec.scheduling_strategy)
                        if w is not None:
                            driver = self.driver_leases.get(w)
                            self._reclaim_driver_lease(w)
                            if driver is not None:
                                # worker is alive: its queued direct
                                # tasks still complete — no resubmit
                                self._send(driver, P.LEASE_REVOKED,
                                           {"worker": w, "dead": False})
                            self._sched_dirty = True
                        break  # class infeasible right now; try next class
                    q.popleft()
                    self._assign_node(tid, t, node_id)
                if not q:
                    empties.append(key)
            for key in empties:
                del self.ready_queues[key]
        if self._maybe_place_pgs():
            # a freshly-placed gang can unblock queued work pinned to
            # its bundles (actor creations waiting on the reservation):
            # drain once more now instead of waiting for the health
            # loop's forced pass
            self._sched_dirty = True
            self._maybe_schedule()

    def _assign_node(self, tid: bytes, t: PendingTask, node_id: NodeID) -> None:
        t.node_id = node_id
        self.task_table[tid]["state"] = "PENDING_NODE_ASSIGNMENT"
        # phase 2: ensure deps local to the chosen node
        node_b = node_id.binary()
        for _, oid in t.spec.arg_refs:
            b = oid.binary()
            e = self.objects.get(b)
            if e is None or e.inline is not None or e.error is not None:
                continue
            if node_b not in e.locations:
                t.transfers_remaining.add(b)
                self.dep_waiters[b].add(tid)
                self._start_transfer(b, node_b)
        if t.transfers_remaining:
            t.state = "PENDING_TRANSFER"
        else:
            self._dispatch(tid)

    def _dispatch(self, tid: bytes) -> None:
        t = self.tasks.get(tid)
        if t is None or t.node_id is None:
            return
        node = self.nodes.get(t.node_id.binary())
        if node is None or not node.alive:
            self._handle_task_failure(tid, "node died before dispatch")
            return
        if not node.idle_workers:
            # ask the node to start a worker; re-dispatch when it registers.
            # The pool of TASK workers is capped at the node's CPU count
            # (reference: worker_pool.cc sizes to num_cpus) — more workers
            # than cores just adds scheduler churn. Actor-pinned workers are
            # dedicated (reference: dedicated actor workers) and do NOT
            # count against the cap, else long-lived actors starve tasks.
            cap = max(1, int(node.resources.total.get("CPU", 1)))
            task_workers = sum(1 for w in node.all_workers
                               if w not in self.worker_actors)
            # zero-footprint tasks (num_cpus=0, placement-group bundles) are
            # admitted by the scheduler without consuming CPU, so demand can
            # legitimately exceed the cap — every admitted task must get a
            # worker eventually or gang workloads deadlock (reference:
            # a granted lease always gets a worker).
            waiting = len(node.stats.get("wait_worker") or ()) + 1
            if node.starting_workers + task_workers < cap or \
                    node.starting_workers < waiting:
                node.starting_workers += 1
                self._send(node.identity, P.TASK_ASSIGN, {"start_worker": True})
            t.state = "QUEUED_WORKER"
            self._waiting_for_worker(node, tid)
            return
        worker = self._pick_idle_worker(node, t.spec)
        self._dispatch_to_worker(tid, node, worker)

    def _prestart_for_actor_demand(self) -> None:
        """Spawn-ahead for actor bursts (VERDICT r4 #4; reference:
        worker_pool.h:104 PrestartWorkers sized by queued demand): every
        queued actor CREATION will need a fresh dedicated worker, but
        CPU admission only lets ~num_cpus creations run at once — if the
        worker spawn starts inside the admission slot, each wave pays
        full boot latency serially. Counting queued creations and
        spawning that many workers NOW (bounded, zygote-forked in ms)
        means every admitted creation finds a registered idle worker.
        Rate-limited: a pass runs at most once per 250ms."""
        now = time.monotonic()
        if now - self._last_actor_prestart < 0.25:
            return
        pending = 0
        for q in self.ready_queues.values():
            for tid in q:
                t = self.tasks.get(tid)
                if t is not None and t.spec.is_actor_creation:
                    pending += 1
        if not pending:
            return
        self._last_actor_prestart = now
        # bounded spawn-ahead: admission is ~num_cpus wide, so a few
        # dozen warm spares keep the pipeline full; forking the WHOLE
        # backlog at once just builds a 100-deep runqueue whose
        # scheduling thrash slows every boot (measured: 96-wide storm
        # registered workers at 2/s vs 40/s uncontended)
        remaining = min(pending, 48)
        alive = [n for n in self.nodes.values() if n.alive]
        for i, node in enumerate(alive):
            if remaining <= 0:
                break
            # even split of the outstanding demand across nodes, less
            # what each already has ready or starting
            share = -(-remaining // (len(alive) - i))
            ready = len(node.idle_workers) + node.starting_workers
            want = max(0, share - ready)
            for _ in range(want):
                node.starting_workers += 1
                self._send(node.identity, P.TASK_ASSIGN,
                           {"start_worker": True})
            remaining -= share

    def _prestart_workers(self) -> None:
        """Warm the pool when a driver connects (reference:
        prestart_worker_first_driver / worker_pool.cc PrestartWorkers):
        the driver's first task burst then lands on live workers instead
        of paying process-spawn latency serially."""
        target = self.config.prestart_workers
        if target <= 0:
            return
        for node in self.nodes.values():
            if not node.alive:
                continue
            cap = max(1, int(node.resources.total.get("CPU", 1)))
            want = min(target, cap)
            have = len(node.all_workers) + node.starting_workers
            for _ in range(max(0, want - have)):
                node.starting_workers += 1
                self._send(node.identity, P.TASK_ASSIGN,
                           {"start_worker": True})

    def _pick_idle_worker(self, node: NodeInfo, spec) -> bytes:
        """Prefer an idle worker whose last-applied runtime env matches
        the task's (reference: runtime-env-keyed worker pools,
        worker_pool.cc — avoids re-mounting working_dir/py_modules and
        env-var churn on shared workers). Falls back to FIFO."""
        env = getattr(spec, "runtime_env", None)
        key = repr(sorted(env.items())) if env else ""
        for i, w in enumerate(node.idle_workers):
            if self._worker_env.get(w, "") == key:
                del node.idle_workers[i]
                return w
        w = node.idle_workers.popleft()
        self._worker_env[w] = key
        return w

    def _waiting_for_worker(self, node: NodeInfo, tid: bytes) -> None:
        node.stats.setdefault("wait_worker", collections.deque()).append(tid)

    def _drain_waiting_tasks(self, node: NodeInfo) -> None:
        waiting = node.stats.get("wait_worker")
        while waiting and node.idle_workers:
            tid = waiting.popleft()
            if tid in self.tasks:
                worker = self._pick_idle_worker(
                    node, self.tasks[tid].spec)
                self._dispatch_to_worker(tid, node, worker)

    def _dispatch_to_worker(self, tid: bytes, node: NodeInfo, worker: bytes) -> None:
        t = self.tasks[tid]
        if t.spec.is_actor_creation:
            t.worker = worker
            t.state = "RUNNING"
            self.task_table[tid].update(
                state="RUNNING", node=t.node_id.hex() if t.node_id else None,
                started_at=time.time())
            self.recorder.record_task(
                EV.DISPATCHED, t.spec.task_id.hex(), t.spec.trace,
                worker=worker.hex()[:12])
            self._send_dispatch(worker, t)
            aid = t.spec.actor_id.binary()
            info = self.actors.get(aid)
            if info is not None:
                info.state = "STARTING"
                info.node_id = t.node_id
            self.actor_workers[aid] = worker
            self.worker_actors[worker] = aid
            # the node's OOM killer should prefer stateless task workers
            self._send(node.identity, P.WORKER_PINNED,
                       {"worker_identity": worker})
            return
        # open a lease: the task's resource acquisition (made at pick_node)
        # transfers to the lease and is released when the lease closes
        lease = Lease(worker=worker, node_b=node.node_id.binary(),
                      shape_key=t.shape_key or (tid,),
                      resources=self._sched_res(t.spec))
        self.leases[worker] = lease
        self.class_leases[lease.shape_key].add(worker)
        self._dispatch_on_lease(lease, tid, t)
        self._refill_lease(lease)

    def _dispatch_on_lease(self, lease: Lease, tid: bytes, t: PendingTask) -> None:
        t.node_id = NodeID(lease.node_b)
        t.worker = lease.worker
        t.state = "RUNNING"
        lease.inflight.add(tid)
        self.task_table[tid].update(state="RUNNING", node=t.node_id.hex(),
                                    started_at=time.time())
        self.recorder.record_task(
            EV.LEASED, t.spec.task_id.hex(), t.spec.trace,
            worker=lease.worker.hex()[:12],
            queue_s=round(time.monotonic() - t.submitted_at, 6))
        self.recorder.record_task(
            EV.DISPATCHED, t.spec.task_id.hex(), t.spec.trace,
            worker=lease.worker.hex()[:12])
        self._send_dispatch(lease.worker, t)

    def _send_dispatch(self, worker: bytes, t: PendingTask) -> None:
        """Message assembly + send only — callers own all state mutation."""
        inline_args = {}
        errors = {}
        for _, oid in t.spec.arg_refs:
            e = self.objects.get(oid.binary())
            if e is None:
                continue
            if e.error is not None:
                errors[oid.binary()] = e.error
            elif e.inline is not None:
                inline_args[oid.binary()] = e.inline
        self._send(worker, P.TASK_DISPATCH, {
            "spec": t.spec, "inline_args": inline_args, "arg_errors": errors})

    def _lease_housekeeping(self, worker: bytes, lease: Lease) -> None:
        """After a completion on a leased worker: refill its pipeline from
        the class queue, or close the lease when the class has drained."""
        self._refill_lease(lease)
        if not lease.inflight and not lease.blocked and \
                not self.ready_queues.get(lease.shape_key):
            self._close_lease(worker, lease)

    def _close_lease(self, worker: bytes, lease: Lease) -> None:
        self.leases.pop(worker, None)
        peers = self.class_leases.get(lease.shape_key)
        if peers is not None:
            peers.discard(worker)
            if not peers:
                self.class_leases.pop(lease.shape_key, None)
        node = self.nodes.get(lease.node_b)
        if node is not None and node.alive and not lease.blocked:
            # a blocked lease already released its allocation
            self._release_res(NodeID(lease.node_b), lease.resources)
        self._return_worker(worker)

    def _h_task_done(self, identity: bytes, m: dict) -> None:
        tid = m["task_id"]
        self.recorder.record_task(
            EV.FAILED if m.get("error") is not None else EV.FINISHED,
            TaskID(tid).hex(), m.get("trace"),
            worker=identity.hex()[:12])
        # Duplicate executions happen (at-least-once resubmission racing
        # a completion already in flight): lease/worker bookkeeping below
        # must still run for WHICHEVER worker executed, but result
        # recording is first-wins — see _record_result_entry.
        if m.get("driver_leased") and not m.get("is_actor_task"):
            # direct driver-leased execution (flag set at dispatch, so
            # this holds even after the lease was reclaimed): the
            # controller never saw the task — record results and
            # observability only; resources are held by the grant
            self.task_table[tid] = {
                "task_id": TaskID(tid).hex(), "type": "NORMAL_TASK",
                "state": "FAILED" if m.get("error") else "FINISHED",
                "finished_at": time.time(), "leased": True}
            if m.get("error") is not None and m.get("retriable") \
                    and m.get("spec") is not None:
                spec: TaskSpec = m["spec"]
                if spec.max_retries != 0:
                    if spec.max_retries > 0:
                        spec.max_retries -= 1
                    # re-route the retry through the normal scheduler
                    self._h_submit_task(m.get("owner") or identity,
                                        {"spec": spec})
                    return
            recorded = []
            for r in m.get("results", []):
                if r.get("inline") is None and not r.get("node_id"):
                    # owner-local result (inline meta trimmed by the
                    # worker, or a bare error result): the owner holds
                    # the value/error and its lifecycle — no directory
                    # entry, no refcounts (recording an error entry here
                    # would leak it forever: the owner never promoted
                    # these returns, so no deltas ever arrive). A parked
                    # borrower resolves via FETCH_OBJECT, so it must NOT
                    # be woken (and failed) here. Crash-window caveat,
                    # matching the reference's in-process store: if the
                    # worker dies with its direct TASK_RESULT unflushed,
                    # the value is unrecoverable (no controller backup).
                    continue
                if self.refs.is_released(r["object_id"]) and \
                        r["object_id"] not in self._pending_frees:
                    # zero confirmed past the grace window: don't
                    # resurrect. Grace-pending zeros still record — the
                    # deferred free (or a resurrecting +1) decides.
                    # Still wake waiters (pre-change behavior): a parked
                    # get on a freed object should fail now, not hang.
                    recorded.append(r["object_id"])
                    continue
                e = self._entry(r["object_id"])
                e.owner = m.get("owner", identity)
                e.size = r.get("size", 0)
                if r.get("inline") is not None:
                    e.inline = r["inline"]
                if r.get("node_id"):
                    e.locations.add(r["node_id"])
                if m.get("error") is not None and e.inline is None \
                        and not e.locations:
                    # first-wins: a duplicate execution (at-least-once
                    # resubmit) failing on since-freed args must not
                    # poison an object that already has data
                    e.error = m["error"]
                recorded.append(r["object_id"])
            for b in recorded:
                self._object_created(b)
            return
        if m.get("owner_report"):
            # the OWNER reports a task that will never execute (dead
            # actor): record the error objects and wake their waiters —
            # no lease/worker bookkeeping (identity is not an executor)
            self.tasks.pop(tid, None)
            for r in m.get("results", []):
                e = self._entry(r["object_id"])
                e.owner = identity
                e.error = m.get("error")
            for r in m.get("results", []):
                self._object_created(r["object_id"])
            return
        t = self.tasks.pop(tid, None)
        lease = self.leases.get(identity)
        if lease is not None:
            lease.inflight.discard(tid)
        row = self.task_table.get(tid)
        if row is not None:
            row["state"] = "FAILED" if m.get("error") else "FINISHED"
            row["finished_at"] = time.time()
        elif m.get("is_actor_task"):
            # direct actor call: first (and only) controller sighting
            aid_hex = None
            a = self.worker_actors.get(identity)
            if a is not None:
                aid_hex = ActorID(a).hex()
            self.task_table[tid] = {
                "task_id": TaskID(tid).hex(), "type": "ACTOR_TASK",
                "state": "FAILED" if m.get("error") else "FINISHED",
                "actor_id": aid_hex, "finished_at": time.time()}
        if t is not None:
            is_actor_task = t.spec.is_actor_task
            is_actor_creation = t.spec.is_actor_creation
        else:
            is_actor_task = bool(m.get("is_actor_task"))
            is_actor_creation = False
        actor_id_b = self.worker_actors.get(identity)

        # direct actor call that failed retriably: re-route using the spec
        # the worker shipped (no controller-side PendingTask exists)
        if m.get("error") is not None and t is None and m.get("retriable") \
                and m.get("spec") is not None:
            spec: TaskSpec = m["spec"]
            if spec.max_retries != 0:
                if spec.max_retries > 0:
                    spec.max_retries -= 1
                self._submit_actor_task(m.get("owner") or identity, spec)
                return

        # retry path (reference: TaskManager::RetryTaskIfPossible)
        if m.get("error") is not None and t is not None and t.retries_left > 0 \
                and m.get("retriable", False):
            t.retries_left -= 1
            if t.spec.is_actor_task:
                # actor tasks re-route to the actor's worker, never the
                # normal-task scheduler
                t.spec.max_retries = t.retries_left
                self._submit_actor_task(
                    self._find_owner_identity(t, m, identity) or identity,
                    t.spec)
                return
            if lease is None and t.node_id is not None:
                # leased tasks don't own resources (the lease does)
                self._release_res(t.node_id, self._sched_res(t.spec))
            t.node_id = None
            t.worker = None
            t.transfers_remaining.clear()
            self.tasks[tid] = t
            self._enqueue_ready(tid, t)
            if lease is not None:
                self._lease_housekeeping(identity, lease)
            elif not (is_actor_creation or actor_id_b):
                self._return_worker(identity)
            self._maybe_schedule()
            return

        # record results
        owner = (t.spec.owner.binary() if t and t.spec.owner else m.get("owner"))
        results_meta = []
        wake = []
        for r in m.get("results", []):
            if m.get("owner_notified") and r.get("inline") is None \
                    and not r.get("node_id") \
                    and (m.get("error") is None
                         or m.get("is_actor_task")):
                # owner-local result of a direct (actor) call: owner
                # holds it; nothing to record or forward, and any parked
                # borrower resolves via FETCH_OBJECT — not here. Actor
                # call ERRORS are owner-local too (their returns were
                # never promoted — recording would leak the entry);
                # controller-path task errors still record, because
                # their returns were promoted at submit and dep-parked
                # tasks fail fast off the entry.
                continue
            wake.append(r["object_id"])
            if self.refs.is_released(r["object_id"]):
                rb = r["object_id"]
                if self.local_waiters.get(rb) or self.dep_waiters.get(rb):
                    # the release was premature (delta batching can zero
                    # transiently while a waiter's +1 is still in
                    # flight): a waiter holds a live ref, so record the
                    # result and let the count resurrect
                    self.refs.cancel_release(rb)
                elif rb in self._pending_frees:
                    # zero still inside the free-grace window: record
                    # the result normally (keeping the tombstone); the
                    # deferred free — or a resurrecting +1 — decides
                    pass
                else:
                    # the owner already dropped every reference (its
                    # direct TASK_RESULT beat this TASK_DONE): recording
                    # the location would resurrect a dead entry and pin
                    # the extent forever — free it at the producing node
                    if r.get("node_id"):
                        node = self.nodes.get(r["node_id"])
                        if node is not None:
                            self._send(node.identity, P.FREE_OBJECT,
                                       {"object_id": rb})
                    continue
            e = self._entry(r["object_id"])
            e.owner = m.get("owner_identity", identity)
            e.size = r.get("size", 0)
            if r.get("inline") is not None:
                e.inline = r["inline"]
            if r.get("node_id"):
                e.locations.add(r["node_id"])
            if m.get("error") is not None and e.inline is None \
                    and not e.locations:
                # first-wins (duplicate executions; see above)
                e.error = m["error"]
            if t is not None and not t.spec.is_actor_creation:
                e.lineage_task = t.spec  # lineage for reconstruction
            results_meta.append({"object_id": r["object_id"],
                                 "inline": r.get("inline"),
                                 "node_id": r.get("node_id"),
                                 "size": r.get("size", 0),
                                 "error": m.get("error")})
        # resource release + worker return (actors hold their resources for
        # life; failed creations are released in _on_actor_created).
        # Leased workers: top up the pipeline from the class queue, close
        # the lease when both pipeline and queue drain.
        if lease is not None:
            self._lease_housekeeping(identity, lease)
        else:
            if t is not None and t.node_id is not None and not is_actor_task \
                    and not is_actor_creation:
                self._release_res(t.node_id, self._sched_res(t.spec))
            if not is_actor_creation and actor_id_b is None:
                self._return_worker(identity)

        # actor creation completion
        if is_actor_creation and t is not None:
            self._on_actor_created(t, identity, error=m.get("error"))

        # notify the owner so its memory store resolves the future — unless
        # the worker already pushed the result over the direct channel
        if not m.get("owner_notified"):
            owner_identity = self._find_owner_identity(t, m, identity)
            if owner_identity is not None:
                self._send(owner_identity, P.TASK_RESULT, {
                    "task_id": tid, "results": results_meta,
                    "error": m.get("error"),
                    # the controller recorded these results: the owner
                    # must promote owner-local returns to tracked
                    "via_controller": True})
        for b in wake:
            self._object_created(b)
        self._maybe_schedule()

    def _find_owner_identity(self, t: Optional[PendingTask], m: dict,
                             default: bytes) -> Optional[bytes]:
        # DEALER identities ARE binary worker ids in this design, so the
        # owner's WorkerID routes directly — no directory scan needed.
        if t is not None and t.spec.owner is not None:
            return t.spec.owner.binary()
        return m.get("owner")

    def _return_worker(self, identity: bytes) -> None:
        self._sched_dirty = True
        info = self.peers.get(identity)
        if not info:
            return
        node = self.nodes.get(info.get("node_id") or b"")
        if node is None or identity not in node.all_workers:
            return
        waiting = node.stats.get("wait_worker")
        if waiting:
            tid = waiting.popleft()
            if tid in self.tasks:
                self._dispatch_to_worker(tid, node, identity)
                return
        node.idle_workers.append(identity)
        self._grant_parked_leases()

    def _handle_task_failure(self, tid: bytes, reason: str,
                             retriable: bool = True,
                             release_resources: bool = True,
                             exc: Optional[BaseException] = None,
                             oom: bool = False) -> None:
        t = self.tasks.get(tid)
        if t is None:
            return
        if t.node_id is not None and release_resources and \
                t.worker not in self.leases:
            self._release_res(t.node_id, self._sched_res(t.spec))
        if oom:
            # OOM kills spend their own budget, with a delay so the node
            # can shed pressure before the task lands again — transient
            # spikes must not burn max_retries (reference: OOM retry
            # policy is separate, memory_monitor + task_manager)
            if t.oom_retries_left < 0:
                t.oom_retries_left = self.config.task_oom_retries
            if t.oom_retries_left > 0:
                t.oom_retries_left -= 1
                t.worker = None
                t.node_id = None
                t.transfers_remaining.clear()
                timer = threading.Timer(
                    self.config.oom_retry_delay_s,
                    lambda: self.call_on_loop(
                        lambda: self._requeue_after_oom(tid, t)))
                timer.daemon = True
                timer.start()
                return
        elif retriable and t.retries_left > 0:
            t.retries_left -= 1
            t.worker = None
            t.node_id = None
            t.transfers_remaining.clear()
            self._enqueue_ready(tid, t)
            self._maybe_schedule()
            return
        self.tasks.pop(tid, None)
        from ray_tpu.exceptions import TaskError
        err = P.dumps(exc if exc is not None else
                      TaskError(t.spec.name or str(t.spec.function), reason))
        results_meta = []
        for oid in t.spec.return_ids():
            e = self._entry(oid.binary())
            e.error = err
            results_meta.append({"object_id": oid.binary(), "error": err})
            self._object_created(oid.binary())
        owner_identity = self._find_owner_identity(t, {}, b"")
        if owner_identity:
            self._send(owner_identity, P.TASK_RESULT, {
                "task_id": tid, "results": results_meta, "error": err,
                "via_controller": True})
        row = self.task_table.get(tid)
        if row is not None:
            row["state"] = "FAILED"

    def _reconstruct(self, e: ObjectEntry) -> None:
        """Lineage reconstruction: resubmit the creating task (reference:
        ObjectRecoveryManager::RecoverObject + TaskManager::ResubmitTask)."""
        spec = e.lineage_task
        if spec is None:
            return
        tid = spec.task_id.binary()
        if tid in self.tasks:
            return  # already being recomputed
        logger.info("reconstructing object %s via task %s",
                    e.object_id.hex()[:12], spec.task_id.hex()[:12])
        e.lineage_task = None  # avoid infinite loops; re-set on completion
        self._h_submit_task(e.owner or b"", {"spec": spec})

    def _h_cancel_task(self, identity: bytes, m: dict) -> None:
        tid = m["task_id"]
        t = self.tasks.get(tid)
        if t is None:
            return
        from ray_tpu.exceptions import TaskCancelledError
        if t.state in ("PENDING_DEPS", "QUEUED", "PENDING_TRANSFER", "QUEUED_WORKER"):
            self.tasks.pop(tid, None)
            q = self.ready_queues.get(t.shape_key or ())
            if q is not None:
                try:
                    q.remove(tid)
                except ValueError:
                    pass
            if t.node_id is not None:
                self._release_res(t.node_id, self._sched_res(t.spec))
            err = P.dumps(TaskCancelledError(t.spec.task_id))
            results = []
            for oid in t.spec.return_ids():
                e = self._entry(oid.binary())
                e.error = err
                results.append({"object_id": oid.binary(), "error": err})
                self._object_created(oid.binary())
            owner_identity = self._find_owner_identity(t, {}, b"")
            if owner_identity:
                self._send(owner_identity, P.TASK_RESULT,
                           {"task_id": tid, "results": results,
                            "error": err, "via_controller": True})
        elif t.worker is not None:
            # dispatched: tell the worker to skip it if still queued
            # worker-side, or interrupt itself if it is the running task
            # (pipelined leases mean a blind SIGINT could hit a neighbour)
            self._send(t.worker, P.CANCEL_QUEUED,
                       {"task_id": tid, "force": m.get("force", False)})
            if m.get("force"):
                info = self.peers.get(t.worker, {})
                node = self.nodes.get(info.get("node_id") or b"")
                if node is not None:
                    self._send(node.identity, P.CANCEL_TASK, {
                        "pid": node.all_workers.get(t.worker, {}).get("pid"),
                        "force": True})

    # -------------------------------------------------------------- actors
    def _h_create_actor(self, identity: bytes, m: dict) -> None:
        spec: TaskSpec = m["spec"]
        aid = spec.actor_id.binary()
        info = ActorInfo(actor_id=spec.actor_id, spec=spec,
                         name=spec.actor_name, namespace=spec.namespace)
        if spec.actor_name:
            key = (spec.namespace, spec.actor_name)
            if key in self.named_actors:
                self._reply(identity, m["rid"],
                            {"error": f"actor name {spec.actor_name!r} taken"},
                            ok=False)
                return
            self.named_actors[key] = aid
            # named actors are durable: get_actor must resolve them after
            # a controller restart (their worker re-announces the binding)
            self.store.append(("actor", spec))
        self.actors[aid] = info
        self.actor_queues[aid] = collections.deque()
        self._reply(identity, m["rid"], {"ok": True})
        self._h_submit_task(identity, {"spec": spec})

    def _on_actor_created(self, t: PendingTask, worker: bytes,
                          error: Optional[bytes]) -> None:
        aid = t.spec.actor_id.binary()
        info = self.actors.get(aid)
        if info is None:
            return
        if error is not None:
            info.state = "DEAD"
            info.death_cause = "creation failed"
            self._fail_actor_queue(aid, error)
            self.worker_actors.pop(worker, None)
            self.actor_workers.pop(aid, None)
            self._return_worker(worker)
            if t.node_id is not None:
                self._release_res(t.node_id, self._sched_res(t.spec))
            self._publish(f"actor:{t.spec.actor_id.hex()}",
                          {"state": "DEAD", "actor_id": aid})
            self._answer_actor_addr_waiters(aid)
            return
        info.state = "ALIVE"
        if not t.spec.hold_resources and t.node_id is not None:
            # default-resource actor: scheduling CPU released once alive
            self._release_res(t.node_id, self._sched_res(t.spec))
        info.worker_id = WorkerID(worker) if len(worker) == WorkerID.SIZE else None
        self._publish(f"actor:{t.spec.actor_id.hex()}",
                      {"state": "ALIVE", "actor_id": aid})
        self._answer_actor_addr_waiters(aid)
        q = self.actor_queues.get(aid)
        while q:
            caller, spec = q.popleft()
            self._route_actor_task(caller, spec, worker)

    def _submit_actor_task(self, identity: bytes, spec: TaskSpec) -> None:
        aid = spec.actor_id.binary()
        info = self.actors.get(aid)
        if info is None or info.state == "DEAD":
            from ray_tpu.exceptions import ActorDiedError
            err = P.dumps(ActorDiedError(spec.actor_id,
                                         info.death_cause if info else "unknown actor"))
            results = [{"object_id": oid.binary(), "error": err}
                       for oid in spec.return_ids()]
            self._send(identity, P.TASK_RESULT, {
                "task_id": spec.task_id.binary(), "results": results,
                "error": err, "via_controller": True})
            return
        worker = self.actor_workers.get(aid)
        if info.state != "ALIVE" or worker is None:
            self.actor_queues[aid].append((identity, spec))
            return
        self._route_actor_task(identity, spec, worker)

    def _route_actor_task(self, caller: bytes, spec: TaskSpec, worker: bytes) -> None:
        tid = spec.task_id.binary()
        self.tasks[tid] = PendingTask(spec=spec, state="RUNNING", worker=worker,
                                      retries_left=spec.max_retries)
        self.task_table[tid] = {
            "task_id": spec.task_id.hex(), "name": spec.name,
            "state": "RUNNING", "type": "ACTOR_TASK",
            "actor_id": spec.actor_id.hex(), "submitted_at": time.time()}
        inline_args = {}
        errors = {}
        for _, oid in spec.arg_refs:
            e = self.objects.get(oid.binary())
            if e is None:
                continue
            if e.error is not None:
                errors[oid.binary()] = e.error
            elif e.inline is not None:
                inline_args[oid.binary()] = e.inline
        self._send(worker, P.TASK_DISPATCH, {
            "spec": spec, "inline_args": inline_args, "arg_errors": errors})

    def _fail_actor_queue(self, aid: bytes, error: bytes) -> None:
        q = self.actor_queues.get(aid)
        while q:
            caller, spec = q.popleft()
            results = [{"object_id": oid.binary(), "error": error}
                       for oid in spec.return_ids()]
            self._send(caller, P.TASK_RESULT, {
                "task_id": spec.task_id.binary(), "results": results,
                "error": error, "via_controller": True})

    def _h_kill_actor(self, identity: bytes, m: dict) -> None:
        aid = m["actor_id"]
        info = self.actors.get(aid)
        if info is None:
            return
        no_restart = m.get("no_restart", True)
        worker = self.actor_workers.get(aid)
        if no_restart:
            info.spec.max_restarts = 0
        if worker is not None:
            winfo = self.peers.get(worker, {})
            node = self.nodes.get(winfo.get("node_id") or b"")
            if node is not None:
                self._send(node.identity, P.KILL_ACTOR, {
                    "pid": node.all_workers.get(worker, {}).get("pid")})

    def _h_actor_addr(self, identity: bytes, m: dict) -> None:
        """Address long-poll for the direct actor-call path: answer when the
        actor is ALIVE (its worker identity doubles as its direct-channel
        address), immediately if it is already dead."""
        aid = m["actor_id"]
        info = self.actors.get(aid)
        worker = self.actor_workers.get(aid)
        if info is None or info.state == "DEAD":
            from ray_tpu.exceptions import ActorDiedError
            cause = info.death_cause if info else "unknown actor"
            self._reply(identity, m["rid"], {
                "dead": True,
                "error": P.dumps(ActorDiedError(ActorID(aid), cause))})
        elif info.state == "ALIVE" and worker is not None:
            self._reply(identity, m["rid"], {"worker": worker})
        else:
            self.actor_addr_waiters[aid].append((identity, m["rid"]))

    def _answer_actor_addr_waiters(self, aid: bytes) -> None:
        waiters = self.actor_addr_waiters.pop(aid, [])
        if not waiters:
            return
        info = self.actors.get(aid)
        worker = self.actor_workers.get(aid)
        if info is not None and info.state == "ALIVE" and worker is not None:
            for identity, rid in waiters:
                self._reply(identity, rid, {"worker": worker})
        elif info is None or info.state == "DEAD":
            from ray_tpu.exceptions import ActorDiedError
            cause = info.death_cause if info else "unknown actor"
            blob = P.dumps(ActorDiedError(ActorID(aid), cause))
            for identity, rid in waiters:
                self._reply(identity, rid, {"dead": True, "error": blob})
        else:  # still pending (e.g. RESTARTING): keep waiting
            self.actor_addr_waiters[aid] = waiters

    def _h_get_actor(self, identity: bytes, m: dict) -> None:
        key = (m.get("namespace", ""), m["name"])
        aid = self.named_actors.get(key)
        if aid is None:
            self._reply(identity, m["rid"], {"error": "not found"}, ok=False)
        else:
            info = self.actors[aid]
            self._reply(identity, m["rid"], {
                "actor_id": aid, "spec_meta": {
                    "max_concurrency": info.spec.max_concurrency,
                    "is_async": info.spec.is_async_actor,
                    "module": info.spec.function.module,
                    "qualname": info.spec.function.qualname,
                }})

    # ------------------------------------------------- kv / functions / pg
    def _h_kv(self, identity: bytes, m: dict) -> None:
        ns, op = m.get("ns", ""), m["op"]
        table = self.kv[ns]
        if op == "put":
            overwrite = m.get("overwrite", True)
            if not overwrite and m["key"] in table:
                self._reply(identity, m["rid"], {"added": False})
                return
            table[m["key"]] = m["value"]
            self.store.append(("kv_put", ns, m["key"], m["value"]))
            self.store.maybe_compact(self._durable_state)
            self._reply(identity, m["rid"], {"added": True})
        elif op == "get":
            self._reply(identity, m["rid"], {"value": table.get(m["key"])})
        elif op == "del":
            existed = table.pop(m["key"], None) is not None
            if existed:
                self.store.append(("kv_del", ns, m["key"]))
            self._reply(identity, m["rid"], {"deleted": existed})
        elif op == "exists":
            self._reply(identity, m["rid"], {"exists": m["key"] in table})
        elif op == "keys":
            prefix = m.get("prefix", b"")
            self._reply(identity, m["rid"],
                        {"keys": [k for k in table if k.startswith(prefix)]})

    def _h_export_function(self, identity: bytes, m: dict) -> None:
        if m["key"] not in self.functions:
            self.store.append(("fn", m["key"], m["blob"]))
        self.functions[m["key"]] = m["blob"]
        if m.get("rid"):
            self._reply(identity, m["rid"], {"ok": True})

    def _h_fetch_function(self, identity: bytes, m: dict) -> None:
        self._reply(identity, m["rid"], {"blob": self.functions.get(m["key"])})

    def _h_create_pg(self, identity: bytes, m: dict) -> None:
        spec: PlacementGroupSpec = m["spec"]
        b = spec.pg_id.binary()
        self.pgs[b] = spec
        self.pg_creators[b] = identity
        if self.scheduler.reserve_placement_group(spec):
            self.pg_states[b] = "CREATED"
            self._reply(identity, m["rid"], {"state": "CREATED",
                                             "bundle_nodes": [bd.node_id.binary() for bd in spec.bundles],
                                             "bundle_labels": self.scheduler.bundle_labels(spec)})
        else:
            self.pg_states[b] = "PENDING"
            self.pending_pgs.append((identity, spec))
            self._reply(identity, m["rid"], {"state": "PENDING"})

    def _maybe_place_pgs(self) -> int:
        """Retry pending gang reservations; returns how many placed."""
        if not self.pending_pgs:
            return 0
        placed = 0
        still = collections.deque()
        while self.pending_pgs:
            identity, spec = self.pending_pgs.popleft()
            b = spec.pg_id.binary()
            if b not in self.pgs:
                continue
            if self.scheduler.reserve_placement_group(spec):
                self.pg_states[b] = "CREATED"
                placed += 1
                if identity:
                    self._send(identity, P.PG_UPDATE, {
                        "pg_id": b, "state": "CREATED",
                        "bundle_nodes": [bd.node_id.binary() for bd in spec.bundles],
                        "bundle_labels": self.scheduler.bundle_labels(spec)})
            else:
                still.append((identity, spec))
        self.pending_pgs = still
        return placed

    def _reschedule_pgs_on_nodes(self, node_bs) -> int:
        """Gang reservations touching these nodes (a dying host or a
        draining slice) are torn down atomically and re-queued: the
        group goes RESCHEDULING until fresh capacity — typically a new
        slice — admits every bundle again (reference: the GCS pg
        manager reschedules bundles on node death; slice drains reuse
        the same path). Returns how many groups were re-queued."""
        targets = set(node_bs)
        n = 0
        for b, spec in list(self.pgs.items()):
            if self.pg_states.get(b) != "CREATED":
                continue
            if not any(bd.node_id is not None
                       and bd.node_id.binary() in targets
                       for bd in spec.bundles):
                continue
            self.scheduler.release_placement_group(spec.pg_id)
            for bd in spec.bundles:
                bd.node_id = None
            self.pg_states[b] = "RESCHEDULING"
            creator = self.pg_creators.get(b, b"")
            self.pending_pgs.append((creator, spec))
            if creator:
                self._send(creator, P.PG_UPDATE,
                           {"pg_id": b, "state": "RESCHEDULING"})
            n += 1
        if n:
            self._sched_dirty = True
        return n

    def _h_remove_pg(self, identity: bytes, m: dict) -> None:
        b = m["pg_id"]
        self.pgs.pop(b, None)
        self.pg_creators.pop(b, None)
        self.pg_states[b] = "REMOVED"
        self.scheduler.release_placement_group(PlacementGroupID(b))
        self._sched_dirty = True  # freed bundle capacity
        self._reply(identity, m["rid"], {"ok": True})
        self._maybe_schedule()

    # ------------------------------------------------------ cluster health
    def _h_notify_blocked(self, identity: bytes, m: dict) -> None:
        """A worker's serial thread blocked in ray.get inside a task:
        release the lease's cpu so dependent work can run (reference:
        NotifyDirectCallTaskBlocked → raylet releases cpu resources)."""
        if identity in self.driver_leases:
            # direct driver-leased worker blocked in ray.get: free its
            # CPU so dependents can run (same contract as class leases)
            if identity not in self._lease_blocked:
                self._lease_blocked.add(identity)
                nb = self._lease_node.get(identity)
                if nb:
                    self._release_res(NodeID(nb), {"CPU": 1.0})
                self._maybe_schedule()
            return
        lease = self.leases.get(identity)
        if lease is None or lease.blocked:
            return
        lease.blocked = True
        node = self.nodes.get(lease.node_b)
        if node is not None and node.alive:
            self._release_res(NodeID(lease.node_b), lease.resources)
        self._maybe_schedule()

    def _h_notify_unblocked(self, identity: bytes, m: dict) -> None:
        if identity in self._blocked_orphans:
            # lease was reclaimed while this worker sat in ray.get; it
            # is now resumable — rejoin the pool (its CPU was already
            # released at block time and stays released until a new
            # dispatch acquires it)
            self._blocked_orphans.discard(identity)
            self._return_worker(identity)
            return
        if identity in self._lease_blocked:
            self._lease_blocked.discard(identity)
            nb = self._lease_node.get(identity)
            if nb:
                self.scheduler.force_acquire(NodeID(nb), {"CPU": 1.0})
            return
        lease = self.leases.get(identity)
        if lease is None or not lease.blocked:
            return
        lease.blocked = False
        # re-acquire, allowing transient oversubscription (the reference
        # resumes the task immediately too; availability self-corrects as
        # other tasks release)
        self.scheduler.force_acquire(NodeID(lease.node_b), lease.resources)
        self._lease_housekeeping(identity, lease)

    def _h_task_handback(self, identity: bytes, m: dict) -> None:
        """A blocking worker returned its unstarted pipeline tasks."""
        if m.get("blocked"):
            # the sender's serial thread is in ray.get RIGHT NOW: make
            # sure its lease is marked so refill stops targeting it
            # (idempotent; heals any missed NOTIFY_BLOCKED)
            lease = self.leases.get(identity)
            if lease is not None and not lease.blocked:
                lease.blocked = True
                node = self.nodes.get(lease.node_b)
                if node is not None and node.alive:
                    self._release_res(NodeID(lease.node_b),
                                      lease.resources)
            elif identity in self.driver_leases \
                    and identity not in self._lease_blocked:
                self._lease_blocked.add(identity)
                nb = self._lease_node.get(identity)
                if nb:
                    self._release_res(NodeID(nb), {"CPU": 1.0})
        requeued = False
        for spec in m.get("specs", ()):
            tid = spec.task_id.binary()
            t = self.tasks.get(tid)
            if t is None:
                if tid not in self.task_table:
                    # direct dispatch bounced by a blocked worker (the
                    # lease may already be reclaimed — adopt anyway; a
                    # handed-back spec vanishing strands its owner)
                    self._h_submit_task(
                        spec.owner.binary() if spec.owner else identity,
                        {"spec": spec})
                    requeued = True
                continue
            if t.worker != identity or t.state != "RUNNING":
                continue
            lease = self.leases.get(identity)
            if lease is not None:
                lease.inflight.discard(tid)
            t.worker = None
            t.node_id = None
            self._enqueue_ready(tid, t)
            requeued = True
        if requeued:
            self._maybe_schedule()

    def _h_ping(self, identity: bytes, m: dict) -> None:
        info = self.peers.get(identity)
        if info is not None:
            info["last_seen"] = time.monotonic()

    def _h_heartbeat(self, identity: bytes, m: dict) -> None:
        node = self.nodes.get(m["node_id"])
        if node is not None:
            node.last_heartbeat = time.monotonic()
            node.stats.update(m.get("stats") or {})

    def _h_worker_exit(self, identity: bytes, m: dict) -> None:
        """Node manager reports a worker process died."""
        worker_identity = m.get("worker_identity")
        if worker_identity and self._reliable is not None:
            # peer-death notice: the task failover below is the
            # recovery — abandon retransmits into the dead worker
            self._reliable.drop_target(worker_identity)
        node = self.nodes.get(m.get("node_id") or b"")
        if node is not None and worker_identity in node.all_workers:
            del node.all_workers[worker_identity]
            self._worker_env.pop(worker_identity, None)
            driver = self.driver_leases.pop(worker_identity, None)
            self._blocked_orphans.discard(worker_identity)
            if driver is not None:
                nb = self._lease_node.pop(worker_identity, None)
                if nb and worker_identity not in self._lease_blocked:
                    self._release_res(NodeID(nb), {"CPU": 1.0})
                self._lease_blocked.discard(worker_identity)
                # the lease owner must resubmit in-flight direct tasks
                self._send(driver, P.LEASE_REVOKED,
                           {"worker": worker_identity, "dead": True})
            try:
                node.idle_workers.remove(worker_identity)
            except ValueError:
                pass
        elif node is not None and m.get("requested"):
            # a worker WE requested died before registering: it was still
            # counted as starting — without this, waiting tasks never get
            # a replacement (node-initiated initial workers were never
            # counted, so those must not decrement)
            node.starting_workers = max(0, node.starting_workers - 1)
        self.peers.pop(worker_identity, None)
        aid = self.worker_actors.pop(worker_identity, None)
        # close any lease first: its single resource allocation is released
        # here, so per-task failure handling must not release again
        lease = self.leases.pop(worker_identity, None)
        if lease is not None:
            peers_set = self.class_leases.get(lease.shape_key)
            if peers_set is not None:
                peers_set.discard(worker_identity)
            lnode = self.nodes.get(lease.node_b)
            if lnode is not None and lnode.alive and not lease.blocked:
                self._release_res(NodeID(lease.node_b), lease.resources)
        # fail/retry every in-flight task dispatched to that worker
        oom = m.get("reason") == "oom"
        for tid, t in list(self.tasks.items()):
            if t.worker != worker_identity:
                continue
            if t.spec.is_actor_task:
                self._on_actor_worker_died(worker_identity, tid)
            elif t.spec.is_actor_creation:
                # actor restart path owns resubmission (below)
                self.tasks.pop(tid, None)
            elif oom:
                # memory-monitor kill: retries from the OOM budget with
                # backoff, surfacing OutOfMemoryError once exhausted
                from ray_tpu.exceptions import OutOfMemoryError
                self._handle_task_failure(
                    tid, "worker killed by the node memory monitor",
                    release_resources=lease is None, oom=True,
                    exc=OutOfMemoryError(
                        f"task {t.spec.name or ''} was killed by the node "
                        f"memory monitor: node memory usage exceeded "
                        f"{self.config.memory_usage_threshold:.0%}"))
            else:
                self._handle_task_failure(tid, "worker died during execution",
                                          release_resources=lease is None)
        if aid is not None:
            self._on_actor_died(aid, worker_identity)
        # tasks already queued for a worker on this node must not strand:
        # the dead worker can't serve them and nothing else re-requests
        # a replacement (common under the OOM killer)
        if node is not None and node.alive:
            waiting = node.stats.get("wait_worker")
            if waiting and not node.idle_workers \
                    and node.starting_workers < len(waiting):
                node.starting_workers += 1
                self._send(node.identity, P.TASK_ASSIGN,
                           {"start_worker": True})
        self._maybe_schedule()

    def _on_actor_worker_died(self, worker_identity: bytes, tid: bytes) -> None:
        t = self.tasks.pop(tid, None)
        if t is None:
            return
        from ray_tpu.exceptions import ActorDiedError, ActorUnavailableError
        info = self.actors.get(t.spec.actor_id.binary())
        will_restart = info is not None and info.state != "DEAD" and (
            info.spec.max_restarts < 0
            or info.num_restarts < info.spec.max_restarts)
        if will_restart:
            # the actor is coming back: the racing call is unavailable,
            # not dead — callers holding the handle may retry
            err = P.dumps(ActorUnavailableError(
                t.spec.actor_id, "actor worker died mid-call; the actor "
                "is restarting"))
        else:
            err = P.dumps(ActorDiedError(t.spec.actor_id, "worker died"))
        results = [{"object_id": oid.binary(), "error": err}
                   for oid in t.spec.return_ids()]
        owner_identity = self._find_owner_identity(t, {}, b"")
        if owner_identity:
            self._send(owner_identity, P.TASK_RESULT, {
                "task_id": tid, "results": results, "error": err,
                "via_controller": True})

    def _on_actor_died(self, aid: bytes, worker_identity: bytes) -> None:
        """Actor restart state machine (reference: gcs_actor_manager.h
        :249-281)."""
        info = self.actors.get(aid)
        if info is None:
            return
        self.actor_workers.pop(aid, None)
        if info.node_id is not None and info.spec.hold_resources:
            self._release_res(info.node_id, self._sched_res(info.spec))
        if info.num_restarts < info.spec.max_restarts or info.spec.max_restarts < 0:
            info.num_restarts += 1
            info.state = "RESTARTING"
            self._publish(f"actor:{info.actor_id.hex()}",
                          {"state": "RESTARTING", "actor_id": aid})
            self._h_submit_task(b"", {"spec": info.spec})
        else:
            info.state = "DEAD"
            info.death_cause = "worker process died"
            self._publish(f"actor:{info.actor_id.hex()}",
                          {"state": "DEAD", "actor_id": aid})
            self._answer_actor_addr_waiters(aid)
            from ray_tpu.exceptions import ActorDiedError
            err = P.dumps(ActorDiedError(info.actor_id, info.death_cause))
            self._fail_actor_queue(aid, err)
            if info.name:
                self.named_actors.pop((info.namespace, info.name), None)
                self.store.append(("actor_dead", aid))

    def _health_loop(self) -> None:
        cfg = self.config
        period = cfg.health_check_period_ms / 1000.0
        threshold = cfg.health_check_failure_threshold * period + \
            cfg.health_check_timeout_ms / 1000.0
        while not self._shutdown.wait(period):
            now = time.monotonic()
            # self-healing backstops: a missed dirty-mark or a stranded
            # dep-parked task can only delay work by one period
            try:
                self.call_on_loop(lambda: self._maybe_schedule(force=True))
                self.call_on_loop(self._audit_parked_tasks)
                self.call_on_loop(self._audit_parked_waiters)
                self.call_on_loop(self._audit_driver_leases)
                self.call_on_loop(self._drain_pending_frees)
            except Exception:
                pass
            try:
                from ray_tpu.core.metric_defs import update_from_state
                update_from_state(controller=self)
            except Exception:
                pass
            # the controller's own registry joins the fleet plane
            # through the same reporter path every other process uses
            try:
                self.metrics_reporter.maybe_report()
            except Exception:
                pass
            for node in list(self.nodes.values()):
                if node.alive and node.last_heartbeat and \
                        now - node.last_heartbeat > threshold:
                    self._on_node_dead(node)
            # recovered named actors whose workers never re-announced
            # within the grace window died during the controller's
            # downtime: run the normal death/restart state machine so
            # get_actor waiters aren't parked forever
            if self._recovered_actors and \
                    now - self._started_at > max(15.0, threshold):
                stale = list(self._recovered_actors)
                self._recovered_actors.clear()
                for aid in stale:
                    try:
                        self.call_on_loop(
                            lambda a=aid: self._expire_recovered_actor(a))
                    except Exception:
                        logger.exception("recovered-actor expiry failed")

    def _audit_parked_tasks(self) -> None:
        """Backstop against stranded PENDING_DEPS tasks: a task whose dep
        arrived without a wake resumes; one whose dep is reconstructable
        reconstructs; one whose dep is gone for good fails loudly with
        ObjectLostError instead of hanging forever."""
        now = time.monotonic()
        for tid, t in list(self.tasks.items()):
            if t.state != "PENDING_DEPS" or not t.deps_remaining:
                continue
            # healthy producers are excluded via _object_expected below,
            # so a moderate age gate suffices (repairing a real
            # directory hole within ~15s instead of minutes)
            if now - (t.submitted_at or now) < 15.0:
                continue
            for b in list(t.deps_remaining):
                e = self.objects.get(b)
                if e is not None and (e.inline is not None
                                      or e.error is not None
                                      or e.locations):
                    # dep exists but the wake was missed
                    self._object_created(b)
                elif e is not None and e.lineage_task is not None:
                    self._reconstruct(e)
                elif e is None:
                    if self._object_expected(b):
                        # the producing task is tracked and alive: this
                        # is a healthy dependency wait, not a hole
                        t._audit_strikes = 0
                        continue
                    # strike 1: probe node stores — a producer killed
                    # between storing the object and reporting it leaves
                    # the bytes resident with no directory entry; the
                    # node re-announces and the task resumes.
                    # many strikes later: genuinely gone — fail loudly.
                    strikes = getattr(t, "_audit_strikes", 0) + 1
                    t._audit_strikes = strikes
                    if strikes in (1, 5, 30):
                        self._probe_nodes_for(b)
                        continue
                    if strikes < 300:
                        continue
                    self.dep_waiters.pop(b, None)
                    from ray_tpu.exceptions import ObjectLostError
                    self._handle_task_failure(
                        tid, f"dependency {ObjectID(b).hex()[:12]} was "
                        f"freed or lost before the task could run",
                        retriable=False,
                        exc=ObjectLostError(
                            ObjectID(b), "freed before dependent task "
                            "could run"))
                    break

    def _probe_nodes_for(self, object_id_b: bytes) -> None:
        for node in self.nodes.values():
            if node.alive:
                self._send(node.identity, P.LOCATE_OBJECT,
                           {"object_id": object_id_b})

    def _object_expected(self, object_id_b: bytes) -> bool:
        """True if a tracked pending/running task will produce this
        object — waiters on it are healthy, not stranded."""
        try:
            tid = ObjectID(object_id_b).task_id().binary()
        except Exception:
            return False
        return tid in self.tasks

    def _audit_parked_waiters(self) -> None:
        """Backstop for gets parked on objects the directory never
        learned about (producer killed between store and report): probe
        node stores after a minute, fail with ObjectLostError if the
        probes come back empty. Also drops waiters whose client is
        gone."""
        now = time.monotonic()
        for b in list(self._waiter_since):
            waiters = self.local_waiters.get(b)
            if not waiters or self.objects.get(b) is not None:
                self._waiter_since.pop(b, None)
                self._hole_strikes.pop(b, None)
                continue
            live = [(ident, rid) for ident, rid in waiters
                    if ident in self.peers]
            if not live:
                self.local_waiters.pop(b, None)
                self._waiter_since.pop(b, None)
                self._hole_strikes.pop(b, None)
                continue
            self.local_waiters[b] = live
            owner_b = self._owner_fetches.get(b)
            if owner_b is not None and owner_b not in self.peers:
                # waiting on an owner-local object whose owner is gone:
                # nothing can ever publish it — fail fast (reference:
                # OwnerDiedError semantics for in-process-store objects)
                from ray_tpu.exceptions import ObjectLostError
                err = P.dumps(ObjectLostError(
                    ObjectID(b), "the object's owner died before "
                    "publishing this owner-local object"))
                for ident, rid in self.local_waiters.pop(b, []):
                    self._reply(ident, rid, {"error": err})
                self._owner_fetches.pop(b, None)
                self._waiter_since.pop(b, None)
                self._hole_strikes.pop(b, None)
                continue
            if now - self._waiter_since[b] < 15.0:
                continue
            if self._object_expected(b):
                # the producing task is tracked and alive — healthy wait
                self._hole_strikes.pop(b, None)
                continue
            strikes = self._hole_strikes.get(b, 0) + 1
            self._hole_strikes[b] = strikes
            if owner_b is not None and strikes in (1, 5, 30):
                # re-ask a live owner (the first FETCH_OBJECT may have
                # been dropped in a reconnect window)
                self._send(owner_b, P.FETCH_OBJECT, {"object_id": b})
            if strikes in (1, 5, 30):
                # cheap repair probes; directory holes (producer killed
                # between store and report) resolve on the first one
                self._probe_nodes_for(b)
            elif strikes >= 300:
                # ~5 minutes with no probe hit and no tracked producer:
                # give up loudly instead of hanging the get forever
                from ray_tpu.exceptions import ObjectLostError
                err = P.dumps(ObjectLostError(
                    ObjectID(b), "no node store holds this object"))
                for ident, rid in self.local_waiters.pop(b, []):
                    self._reply(ident, rid, {"error": err})
                self._waiter_since.pop(b, None)
                self._hole_strikes.pop(b, None)

    def _requeue_after_oom(self, tid: bytes, t: PendingTask) -> None:
        if self.tasks.get(tid) is not t:
            return  # cancelled/failed while the backoff timer ran
        self._enqueue_ready(tid, t)
        self._maybe_schedule()

    def _expire_recovered_actor(self, aid: bytes) -> None:
        info = self.actors.get(aid)
        if info is not None and info.state == "RESTARTING":
            logger.warning(
                "recovered actor %s never re-announced; declaring its "
                "worker dead", ActorID(aid).hex()[:12])
            self._on_actor_died(aid, b"")

    def _on_node_dead(self, node: NodeInfo) -> None:
        logger.warning("node %s declared dead", node.node_id.hex()[:12])
        if self._reliable is not None:
            self._reliable.drop_target(node.identity)
        node.alive = False
        node.resources.alive = False
        self.scheduler.remove_node(node.node_id)
        self._publish("node", {"event": "removed",
                               "node_id": node.node_id.binary()})
        node_b = node.node_id.binary()
        # prune object locations; lost objects get lazily reconstructed
        for e in self.objects.values():
            e.locations.discard(node_b)
        # fail/retry tasks running there
        for worker_identity in list(node.all_workers):
            self._h_worker_exit(node.identity, {
                "worker_identity": worker_identity, "node_id": node_b})
        # gang reservations that spanned this host reschedule as a unit
        # (a preempted slice host strands its whole placement group)
        if self._reschedule_pgs_on_nodes({node_b}):
            self._maybe_schedule()

    # -------------------------------------------------------- observability
    def _h_state_query(self, identity: bytes, m: dict) -> None:
        self._reply(identity, m["rid"], {
            "rows": self.state_rows(m["what"], m.get("limit"),
                                    m.get("params"))})

    def state_rows(self, what: str, limit: Optional[int] = None,
                   params: Optional[dict] = None):
        """Loop-thread-only state snapshot (shared by the wire state
        API and the dashboard head, which holds a direct reference).
        The ``metrics*`` views only touch the internally-locked
        MetricsPlane, so they are safe from any thread."""
        if what == "metrics":
            return self.metrics_plane.catalog()
        if what == "metrics_query":
            p = params or {}
            return self.metrics_plane.query(
                p.get("name", ""),
                window_s=float(p.get("window_s", 60.0)),
                agg=p.get("agg"))
        if what == "metrics_fleet":
            p = params or {}
            return self.metrics_plane.fleet_summary(
                window_s=float(p.get("window_s", 30.0)))
        if what == "metrics_latest":
            return self.metrics_plane.latest_samples(
                (params or {}).get("name", ""))
        # request-trace views only touch the internally-locked
        # RequestTraceStore — safe from any thread, like metrics*.
        if what == "requests":
            return self.request_traces.rows(limit=limit or 50)
        if what == "request_trace":
            w = self.request_traces.waterfall(
                (params or {}).get("request_id", ""))
            return [w] if w is not None else []
        m = {"limit": limit} if limit else {}
        if what == "nodes":
            rows = [{
                "node_id": n.node_id.hex(), "alive": n.alive,
                "resources_total": n.resources.total,
                "resources_available": n.resources.available,
                "labels": dict(n.resources.labels),
                "num_workers": len(n.all_workers), "stats": dict(n.stats, wait_worker=None),
            } for n in self.nodes.values()]
        elif what == "node_processes":
            # per-node-agent process stats (reference: the reporter
            # agent's per-process psutil feed, flattened per worker)
            rows = []
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for p in n.stats.get("processes") or []:
                    rows.append(dict(p, node_id=n.node_id.hex()))
        elif what == "tasks":
            rows = list(self.task_table.values())[-m.get("limit", 1000):]
        elif what == "actors":
            rows = [{
                "actor_id": a.actor_id.hex(), "state": a.state,
                "name": a.name, "namespace": a.namespace,
                "num_restarts": a.num_restarts,
                "node_id": a.node_id.hex() if a.node_id else None,
            } for a in self.actors.values()]
        elif what == "objects":
            rows = [{
                "object_id": e.object_id.hex(), "size": e.size,
                "inline": e.inline is not None,
                "locations": [l.hex()[:12] for l in e.locations],
                "has_error": e.error is not None,
            } for e in list(self.objects.values())[:m.get("limit", 1000)]]
        elif what == "placement_groups":
            rows = [{
                "pg_id": PlacementGroupID(b).hex(), "state": self.pg_states.get(b),
                "strategy": spec.strategy, "name": spec.name,
                "bundles": [bd.resources for bd in spec.bundles],
                "bundle_nodes": [bd.node_id.hex() if bd.node_id else None
                                 for bd in spec.bundles],
                "bundle_labels": self.scheduler.bundle_labels(spec),
            } for b, spec in self.pgs.items()]
        elif what == "jobs":
            rows = list(self.jobs.values())
        elif what == "cluster_resources":
            rows = self.scheduler.cluster_resources()
        elif what == "available_resources":
            rows = self.scheduler.available_resources()
        elif what == "timeline":
            rows = self.task_events[-m.get("limit", 100_000):]
        elif what == "task_events":
            # merged flight-recorder stream: pull the controller's own
            # buffered events in first so the snapshot is fresh
            self.recorder.flush()
            with self._events_lock:
                rows = self.flight_events[-m.get("limit", 100_000):]
        else:
            rows = []
        return rows

    # -------------------------------------------------- worker profiling
    def profile_worker(self, worker_identity_b: bytes,
                       duration_s: float = 2.0,
                       timeout_s: float = 30.0) -> Optional[dict]:
        """Ask a worker to sample its own stacks and return the
        collapsed-stack flamegraph artifact (reference: the dashboard's
        on-demand py-spy via profile_manager.py:79; here the worker's
        in-process sampler, which needs no external tooling). Called
        from the dashboard's HTTP threads."""
        import os as _os
        rid = _os.urandom(8)
        ev = threading.Event()
        slot: dict = {}
        self._profile_waiters[rid] = (ev, slot)
        def send_if_known():
            if worker_identity_b not in self.peers:
                # a spawned-but-unregistered worker can't be reached by
                # identity; fail fast instead of timing out
                return False
            self._send(worker_identity_b, P.PROFILE_SELF,
                       {"rid": rid, "duration_s": duration_s})
            return True

        try:
            if not self.call_on_loop(send_if_known):
                return {"error": "worker is not registered "
                        "(still booting, or gone)"}
            if not ev.wait(timeout_s):
                return None
            return slot.get("data")
        finally:
            self._profile_waiters.pop(rid, None)

    def _h_profile_result(self, identity: bytes, m: dict) -> None:
        ent = self._profile_waiters.get(m.get("rid") or b"")
        if ent is not None:
            ent[1]["data"] = m
            ent[0].set()

    def _h_timeline(self, identity: bytes, m: dict) -> None:
        self.task_events.extend(m["events"])
        cap = self.config.task_events_max_buffer
        if len(self.task_events) > cap:
            self.task_events = self.task_events[-cap:]

    def _ingest_events(self, events: List[dict]) -> None:
        """Append flight-recorder events into the bounded aggregation
        buffer (thread-safe: remote TEV batches land on the loop
        thread, the controller's own watermark flushes can fire from
        the reliable layer's thread)."""
        with self._events_lock:
            self.flight_events.extend(events)
            cap = self.config.task_events_max_buffer
            if len(self.flight_events) > cap:
                del self.flight_events[:len(self.flight_events) - cap]

    def _h_task_events(self, identity: bytes, m: dict) -> None:
        self._ingest_events(m.get("events") or [])

    def _h_metric_report(self, identity: bytes, m: dict) -> None:
        """Fleet metrics plane ingest: merge one process's periodic
        snapshot (seq-guarded — exactly-once-effect even past the
        reliable layer's dedup window)."""
        self.metrics_plane.ingest(m)

    def _h_request_spans(self, identity: bytes, m: dict) -> None:
        """Per-request trace ingest: one tail-sampled span batch.
        (request_id, part, seq)-deduped in the store, so a retransmit
        or chaos dup never doubles a waterfall."""
        self.request_traces.ingest(m)

    def _h_subscribe(self, identity: bytes, m: dict) -> None:
        self.subs[m["channel"]].add(identity)

    def _h_pubsub(self, identity: bytes, m: dict) -> None:
        self._publish(m["channel"], m["data"])

    def _publish(self, channel: str, data: Any) -> None:
        for identity in self.subs.get(channel, ()):
            self._send(identity, P.PUBSUB, {"channel": channel, "data": data})
        for identity in self.subs.get("*", ()):
            self._send(identity, P.PUBSUB, {"channel": channel, "data": data})

    def _h_msg_ack(self, identity: bytes, m: dict) -> None:
        if self._reliable is not None:
            self._reliable.on_ack(m)

    def _h_shutdown(self, identity: bytes, m: dict) -> None:
        for node in self.nodes.values():
            self._send(node.identity, P.SHUTDOWN, {})
        self._shutdown.set()

    _HANDLERS = {
        P.REGISTER: _h_register,
        P.SUBMIT_TASK: _h_submit_task,
        P.SUBMIT_BATCH: _h_submit_batch,
        P.TASK_DONE: _h_task_done,
        P.CANCEL_TASK: _h_cancel_task,
        P.CREATE_ACTOR: _h_create_actor,
        P.KILL_ACTOR: _h_kill_actor,
        P.GET_ACTOR: _h_get_actor,
        P.ACTOR_ADDR: _h_actor_addr,
        P.PUT_OBJECT: _h_put_object,
        P.GET_LOCATION: _h_get_location,
        P.PULL_FAILED: _h_pull_failed,
        P.REF_DELTAS: _h_ref_deltas,
        P.OWNER_FREE: _h_owner_free,
        P.LEASE_WORKERS: _h_lease_workers,
        P.RELEASE_LEASES: _h_release_leases,
        P.KV_OP: _h_kv,
        P.EXPORT_FUNCTION: _h_export_function,
        P.FETCH_FUNCTION: _h_fetch_function,
        P.CREATE_PG: _h_create_pg,
        P.REMOVE_PG: _h_remove_pg,
        P.HEARTBEAT: _h_heartbeat,
        P.PROFILE_RESULT: _h_profile_result,
        P.PING: _h_ping,
        P.WORKER_EXIT: _h_worker_exit,
        P.NOTIFY_BLOCKED: _h_notify_blocked,
        P.NOTIFY_UNBLOCKED: _h_notify_unblocked,
        P.TASK_HANDBACK: _h_task_handback,
        P.STATE_QUERY: _h_state_query,
        P.TIMELINE_EVENTS: _h_timeline,
        P.TASK_EVENTS: _h_task_events,
        P.METRIC_REPORT: _h_metric_report,
        P.REQUEST_SPANS: _h_request_spans,
        P.SUBSCRIBE: _h_subscribe,
        P.PUBSUB: _h_pubsub,
        P.MSG_ACK: _h_msg_ack,
        P.SHUTDOWN: _h_shutdown,
    }
