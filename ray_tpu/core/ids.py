"""Binary identifiers for all framework entities.

Modeled on the reference ID scheme (``src/ray/common/id.h`` and
``src/ray/design_docs/id_specification.md``): IDs are fixed-width byte
strings with structural nesting — an ObjectID embeds the TaskID of the task
that created it, a TaskID embeds the ActorID (or a nil actor) and the JobID —
so ownership and lineage can be derived from the ID itself without a lookup.

Sizes (bytes):
    JobID 4, ActorID 16 (= JobID + 12 unique), TaskID 24 (= ActorID + 8
    unique), ObjectID 28 (= TaskID + 4 LE return-index), NodeID 28,
    WorkerID 28, PlacementGroupID 18 (= JobID + 14 unique).
"""

from __future__ import annotations

import os
import random
import threading

# /dev/urandom syscalls cost ~100us in sandboxed environments; ID minting is
# on the task-submission hot path, so draw from a process-local PRNG seeded
# once from the OS (fork-safe: reseeded per pid).
_rng_state = threading.local()


def _rand_bytes(n: int) -> bytes:
    st = getattr(_rng_state, "v", None)
    if st is None or st[0] != os.getpid():
        st = (os.getpid(), random.Random(os.urandom(32)))
        _rng_state.v = st
    return st[1].getrandbits(n * 8).to_bytes(n, "little")

JOB_ID_SIZE = 4
ACTOR_ID_SIZE = 16
TASK_ID_SIZE = 24
OBJECT_ID_SIZE = 28
NODE_ID_SIZE = 28
WORKER_ID_SIZE = 28
PLACEMENT_GROUP_ID_SIZE = 18

_ACTOR_UNIQUE = ACTOR_ID_SIZE - JOB_ID_SIZE
_TASK_UNIQUE = TASK_ID_SIZE - ACTOR_ID_SIZE
_PG_UNIQUE = PLACEMENT_GROUP_ID_SIZE - JOB_ID_SIZE


class BaseID:
    """A fixed-width binary ID. Immutable, hashable, comparable."""

    SIZE = 0
    __slots__ = ("_bytes",)

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got "
                f"{len(binary) if isinstance(binary, bytes) else type(binary)}"
            )
        self._bytes = binary

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_rand_bytes(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def __hash__(self):
        return hash(self._bytes)

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = JOB_ID_SIZE
    __slots__ = ()

    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._bytes, "little")


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + _rand_bytes(_ACTOR_UNIQUE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JOB_ID_SIZE])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE
    __slots__ = ()

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(ActorID.nil().binary()[:ACTOR_ID_SIZE - JOB_ID_SIZE]
                   + job_id.binary() + _rand_bytes(_TASK_UNIQUE))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + _rand_bytes(_TASK_UNIQUE))

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        # The driver's implicit "main" task; return-index 0 objects from it
        # are `put()` objects.
        return cls(ActorID.nil().binary()[:ACTOR_ID_SIZE - JOB_ID_SIZE]
                   + job_id.binary() + b"\x00" * _TASK_UNIQUE)

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:ACTOR_ID_SIZE])


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE
    __slots__ = ()

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index to avoid colliding with
        # return objects (reference: ObjectID::FromIndex semantics).
        return cls(task_id.binary() + (put_index | 0x80000000).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TASK_ID_SIZE:], "little") & 0x7FFFFFFF

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bytes[TASK_ID_SIZE:], "little") & 0x80000000)


class NodeID(BaseID):
    SIZE = NODE_ID_SIZE
    __slots__ = ()


class WorkerID(BaseID):
    SIZE = WORKER_ID_SIZE
    __slots__ = ()


class PlacementGroupID(BaseID):
    SIZE = PLACEMENT_GROUP_ID_SIZE
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(job_id.binary() + _rand_bytes(_PG_UNIQUE))
