"""Warm worker factory: a fork-server ("zygote") that pre-imports the
worker stack once and forks ready-to-run worker processes in
milliseconds.

Reference: ``src/ray/raylet/worker_pool.h:104`` — the raylet prestarts
and reuses workers precisely because a cold Python worker boot
(interpreter + imports) costs seconds. Prestart hides that latency for
the steady state; this zygote removes it from the SPAWN path itself,
which is what an actor burst hits: every actor needs a fresh dedicated
worker, so 120 actors at ~2.5s of import CPU each serialize into
minutes on a small host. Forking from a warmed template costs ~5ms and
shares the imported pages copy-on-write.

Fork-safety: the zygote imports modules but starts NO threads and
creates NO zmq contexts — the forked child builds its Runtime (threads,
sockets) from scratch after the fork. The child double-forks so the
zygote never accumulates zombies (init reaps the grandchild); the
grandchild reports its own pid over the spawn connection before
entering the worker main loop.

Protocol (unix stream socket, one spawn per connection):
  request  = JSON line {"env": {...}, "log_path": str}
  response = JSON line {"pid": int}
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys


def _become_worker(req: dict, conn: socket.socket) -> None:
    """Grandchild: finish detaching, report our pid, run the worker."""
    os.setsid()
    fd = os.open(req["log_path"],
                 os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    if fd > 2:
        os.close(fd)
    env = req.get("env") or {}
    os.environ.update(env)
    for p in reversed(env.get("PYTHONPATH", "").split(os.pathsep)):
        if p and p not in sys.path:
            sys.path.insert(0, p)
    signal.signal(signal.SIGINT, signal.SIG_DFL)
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    try:
        conn.sendall((json.dumps({"pid": os.getpid()}) + "\n").encode())
    finally:
        conn.close()
    try:
        from ray_tpu.core import worker
        worker.main()
    except BaseException:  # noqa: BLE001
        import traceback
        traceback.print_exc()
    finally:
        os._exit(0)


def serve(sock_path: str, parent_pid: int = 0) -> None:
    # Pre-import the whole worker stack (the expensive part a cold
    # worker pays: interpreter is already up here, so this is the only
    # boot cost left) BEFORE accepting spawns. Must not start threads.
    import ray_tpu.core.worker  # noqa: F401

    # Fork-server GC hygiene: freeze the warmed heap into the permanent
    # generation. Without this every child's first gen-2 collection
    # walks the ~200k inherited objects — burning ~250ms CPU per worker
    # AND unsharing the copy-on-write pages the zygote exists to share
    # (the measured actor-burst ceiling). Children collect only their
    # own allocations.
    import gc
    gc.collect()
    gc.freeze()

    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv.bind(sock_path)
    srv.listen(128)
    # parent-death watch: poll the node manager's pid between accepts —
    # without it every unclean node death (SIGKILL, crash) leaks a full
    # pre-imported interpreter plus its socket
    srv.settimeout(5.0)
    while True:
        if parent_pid:
            try:
                os.kill(parent_pid, 0)
            except ProcessLookupError:
                try:
                    os.unlink(sock_path)
                except OSError:
                    pass
                return
            except PermissionError:
                pass
        try:
            conn, _ = srv.accept()
        except socket.timeout:
            continue
        except OSError:
            return
        try:
            data = b""
            while not data.endswith(b"\n"):
                chunk = conn.recv(65536)
                if not chunk:
                    break
                data += chunk
            if not data.strip():
                continue
            req = json.loads(data)
            if req.get("op") == "shutdown":
                conn.close()
                return
            pid = os.fork()
            if pid == 0:
                # intermediate child: fork again and exit so the worker
                # is reparented to init (no zombies in the zygote)
                srv.close()
                if os.fork() != 0:
                    os._exit(0)
                _become_worker(req, conn)
            os.waitpid(pid, 0)  # reap the intermediate
        except Exception:  # noqa: BLE001
            import traceback
            traceback.print_exc()
        finally:
            try:
                conn.close()
            except OSError:
                pass


def main() -> None:
    serve(sys.argv[1],
          int(sys.argv[2]) if len(sys.argv) > 2 else 0)


if __name__ == "__main__":
    main()
