"""ObjectRef: a future-like distributed reference to an immutable object.

Equivalent of the reference's ``ObjectRef`` (``python/ray/includes/
object_ref.pxi``): hashable, awaitable, picklable. Pickling a ref inside
another object triggers the *borrowing* protocol (reference:
``src/ray/core_worker/reference_count.h:61``): the serializer records the
contained ref, and the deserializing process registers itself as a borrower
with the owner so the object is not freed while borrowed.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID, WorkerID


class ObjectRef:
    __slots__ = ("_id", "_owner", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[WorkerID] = None,
                 _register: bool = True):
        self._id = object_id
        self._owner = owner
        self._registered = False
        if _register:
            ctx = _get_refcount_context()
            if ctx is not None:
                ctx.add_local_reference(self)
                self._registered = True

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    @property
    def owner(self) -> Optional[WorkerID]:
        return self._owner

    def task_id(self):
        return self._id.task_id()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"

    def __del__(self):
        if self._registered:
            try:
                ctx = _get_refcount_context()
                if ctx is not None:
                    ctx.remove_local_reference(self)
            except Exception:
                pass

    def __reduce__(self):
        # Custom reducer: route through the serialization context so
        # contained refs are recorded for borrowing. Direct pickling (outside
        # a SerializationContext) reconstructs a non-registered ref.
        from ray_tpu.core import serialization
        ctx = serialization.get_active_context()
        if ctx is not None:
            ctx.record_contained_ref(self)
        # any serialization means the ref may leave this process — the
        # owner loses the right to eagerly free the object
        from ray_tpu.core.global_state import try_global_worker
        w = try_global_worker()
        if w is not None:
            w.mark_ref_escaped(self._id.binary())
        return (_deserialize_ref, (self._id.binary(), self._owner.binary() if self._owner else None))

    def __await__(self):
        return self.as_future().__await__()

    def as_future(self):
        import asyncio
        from ray_tpu.core.global_state import global_worker
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        w = global_worker()

        def _done(value, err):
            def _set():
                if fut.cancelled():
                    return
                if err is not None:
                    fut.set_exception(err)
                else:
                    fut.set_result(value)
            loop.call_soon_threadsafe(_set)

        w.register_completion_callback(self, _done)
        return fut

    def future(self):
        """concurrent.futures.Future view (reference: ObjectRef.future())."""
        import concurrent.futures
        from ray_tpu.core.global_state import global_worker
        fut = concurrent.futures.Future()
        w = global_worker()

        def _done(value, err):
            if err is not None:
                fut.set_exception(err)
            else:
                fut.set_result(value)

        w.register_completion_callback(self, _done)
        return fut


def _deserialize_ref(id_binary: bytes, owner_binary):
    from ray_tpu.core import serialization
    owner = WorkerID(owner_binary) if owner_binary else None
    ref = ObjectRef(ObjectID(id_binary), owner)
    ctx = serialization.get_active_context()
    if ctx is not None:
        ctx.record_deserialized_ref(ref)
    return ref


def _get_refcount_context():
    from ray_tpu.core.global_state import try_global_worker
    w = try_global_worker()
    if w is None:
        return None
    return w.reference_counter
