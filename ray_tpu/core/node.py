"""Node manager: per-node daemon for worker lifecycle and the object store.

Equivalent of the reference's raylet (``src/ray/raylet/node_manager.cc``)
minus scheduling (which lives in the controller here): it spawns/monitors
worker processes (``worker_pool.h:104``), owns the shared-memory store's
eviction/spill authority (plasma runs inside the raylet in the reference,
``object_manager.cc:32``), serves object push/pull transfers
(``object_manager.h:206``), reports heartbeats, and executes kill/cancel
signals. Runs as a thread inside the head process for the default
single-node ``init()``, or as a standalone process (``python -m
ray_tpu.core.node``) for multi-node clusters and tests (equivalent of
``ray.cluster_utils.Cluster.add_node``).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import zmq

from ray_tpu.core import chaos as CH
from ray_tpu.core import direct as D
from ray_tpu.core import events as EV
from ray_tpu.core import protocol as P
from ray_tpu.core import reliable as RD
from ray_tpu.core.config import Config, get_config
from ray_tpu.core.ids import NodeID, ObjectID, WorkerID
from ray_tpu.core.shm_store import make_client, make_store

logger = logging.getLogger(__name__)


class _ForkedWorker:
    """Popen-shaped handle over a zygote-forked worker. The process is
    reparented to init (double fork), so liveness is probed via /proc —
    and pinned to the process's START TIME: init reaps these workers
    immediately (no zombie holds the pid, unlike Popen children), so a
    recycled pid would otherwise make a dead worker look alive forever
    and let the OOM monitor SIGKILL an unrelated process."""

    @staticmethod
    def _starttime(pid: int) -> Optional[str]:
        try:
            with open(f"/proc/{pid}/stat") as f:
                parts = f.read().rsplit(") ", 1)[-1].split()
            return parts[19]  # starttime: field 22, 20th after comm
        except OSError:
            return None

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._birth = self._starttime(pid)

    def _alive(self) -> bool:
        st = self._starttime(self.pid)
        return st is not None and st == self._birth

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        if self._alive():
            return None
        self.returncode = 0
        return 0

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return self.returncode or 0

    def terminate(self) -> None:
        if not self._alive():
            self.returncode = 0
            return
        try:
            os.kill(self.pid, signal.SIGTERM)
        except ProcessLookupError:
            self.returncode = 0

    def kill(self) -> None:
        if not self._alive():
            # never signal a recycled pid (could be anyone's process)
            self.returncode = 0
            return
        try:
            os.kill(self.pid, signal.SIGKILL)
        except ProcessLookupError:
            self.returncode = 0


class NodeManager:
    def __init__(self, session_dir: str, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 node_id: Optional[NodeID] = None,
                 num_initial_workers: int = 0,
                 config: Optional[Config] = None,
                 env: Optional[Dict[str, str]] = None):
        self.session_dir = session_dir
        self.node_id = node_id or NodeID.from_random()
        self.resources = resources
        self.labels = labels or {}
        self.config = config or get_config()
        self.worker_env = env or {}
        self.shm_session = f"raytpu-{os.path.basename(session_dir)}-{self.node_id.hex()[:8]}"

        capacity = self.config.object_store_memory
        if capacity <= 0:
            try:
                import psutil
                capacity = int(psutil.virtual_memory().total
                               * self.config.object_store_memory_fraction)
            except Exception:
                capacity = 2 << 30
        self.store = make_store(
            self.shm_session, capacity,
            spill_dir=os.path.join(self.config.spill_dir, self.node_id.hex()[:8]))
        self.shm = make_client(self.shm_session)

        self.workers: Dict[bytes, subprocess.Popen] = {}  # identity -> proc
        #: pid -> psutil.Process, persistent so cpu_percent deltas work
        self._psutil_cache: Dict[int, Any] = {}
        self._worker_started: Dict[bytes, float] = {}     # identity -> ts
        self._oom_killed: Dict[bytes, bool] = {}          # identity -> True
        self._requested_workers: set = set()   # controller-requested ids
        self._pinned_workers: set = set()      # actor hosts (OOM-deprioritized)
        self._workers_lock = threading.Lock()
        self._stopped = threading.Event()

        self.ctx = zmq.Context.instance()
        self.sock = self.ctx.socket(zmq.DEALER)
        # node identity: its NodeID binary (distinct size from WorkerID use
        # is fine — identities are opaque to zmq)
        self.identity = b"N" + self.node_id.binary()[:27]
        self.sock.setsockopt(zmq.IDENTITY, self.identity)
        self.sock.setsockopt(zmq.LINGER, 0)
        self.sock.connect(P.socket_path(session_dir))
        self._send_lock = threading.Lock()
        # direct peer channel: object chunks move node-to-node here and
        # NEVER transit the controller (reference: object_manager.h:206
        # pushes between object managers; GCS sees only locations)
        D.ensure_dir(session_dir)
        self.direct_sock = self.ctx.socket(zmq.ROUTER)
        self.direct_sock.setsockopt(zmq.LINGER, 0)
        self.direct_sock.setsockopt(zmq.SNDHWM, 0)
        self.direct_sock.setsockopt(zmq.RCVHWM, 0)
        self.direct_sock.bind(D.direct_addr(session_dir, self.identity))
        self._peer_socks: Dict[bytes, zmq.Socket] = {}  # loop-thread-only
        self._threads: List[threading.Thread] = []
        self.num_initial_workers = num_initial_workers
        self._incoming: Dict[bytes, dict] = {}
        # pull manager (reference: pull_manager.h:52): bytes-budgeted
        # admission so a burst of pulls can't blow out the local store
        self._pull_queue: List[dict] = []
        self._pulling: Dict[bytes, dict] = {}   # object_id -> pull state
        self._pull_bytes_inflight = 0
        # source-side outbound streams, windowed by receiver acks so a
        # huge object never sits fully buffered in zmq send queues
        self._outgoing: Dict[tuple, dict] = {}  # (requester, oid) -> state
        self._peer_last_used: Dict[bytes, float] = {}
        #: pull retries parked by restore-capacity backoff timers;
        #: drained by the message loop (appends are GIL-atomic)
        self._pull_retries: "deque" = deque()
        from queue import SimpleQueue
        self._store_rpc_q: "SimpleQueue" = SimpleQueue()
        self._store_rpc_thread: Optional[threading.Thread] = None
        #: warm worker factory (see core/zygote.py): forks registered
        #: workers in ~ms instead of seconds of interpreter+import boot
        self._zygote: Optional[subprocess.Popen] = None
        self._zygote_sock = os.path.join(
            session_dir, f"zygote-{self.node_id.hex()[:12]}.sock")
        #: spawn requests drain on dedicated spawner threads: the
        #: zygote handshake waits for the forked child to be scheduled
        #: once, which under a deep runqueue takes hundreds of ms — it
        #: must never block the node message loop
        self._spawn_q: "SimpleQueue" = SimpleQueue()
        self._spawner_threads: List[threading.Thread] = []
        self._zygote_started = False
        self._spawn_init_lock = threading.Lock()
        self._spawn_count = 0
        # seeded fault injection (chaos.py): None in production
        self._chaos = CH.maybe_injector("node", self_id=self.identity)
        self._chaos_dedup = CH.SeqDeduper() if self._chaos is not None \
            else None
        #: chaos-delayed direct sends (timer threads) and reliable-layer
        #: direct acks parked here; drained by the message loop (peer
        #: sockets are loop-thread-only)
        self._chaos_delayed: "deque" = deque()
        # flight recorder (core/events.py): the node's contribution is
        # transport-health events (retransmits of its PUT announcements,
        # dedup drops); flushed with the heartbeat
        self.recorder = EV.make_recorder(
            f"node:{self.node_id.hex()[:12]}", self.config,
            send=lambda evs: self._send(P.TASK_EVENTS, {"events": evs}))
        # reliable-delivery sublayer: the node's critical one-way
        # traffic is controller-bound (PUT_OBJECT announcements); it
        # also acks the controller's TASK_ASSIGNs
        self._reliable = RD.maybe_transport(
            self.config, self._reliable_resend, self._reliable_ack,
            rng=self._chaos.rng_for("retransmit")
            if self._chaos is not None else None, name="node",
            recorder=self.recorder)
        # fleet metrics reporter: the node manager's registry (store
        # gauges, transport counters) ships with the heartbeat cadence
        from ray_tpu.util import metrics as MX
        self.metrics_reporter = MX.make_reporter(
            lambda payload: self._send(P.METRIC_REPORT, payload),
            {"node": self.node_id.hex()[:12], "pid": os.getpid(),
             "role": "node"},
            self.config,
            pending_drop=(
                (lambda keep: self._reliable.drop_oldest_of(
                    P.METRIC_REPORT, keep))
                if self._reliable is not None else None))

    # ------------------------------------------------------------------ run
    def _register_with_controller(self) -> None:
        self._send(P.REGISTER, {
            "kind": "node", "id": self.identity,
            "node_id": self.node_id.binary(), "resources": self.resources,
            "labels": self.labels, "pid": os.getpid(),
            "objects": self.store.contents()})

    def _worker_base_env(self) -> Dict[str, str]:
        """Env a worker needs beyond the inherited environment."""
        env: Dict[str, str] = dict(self.worker_env)
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_SHM_SESSION"] = self.shm_session
        # zygote-forked workers are reparented to init: the orphan
        # watchdog must poll this pid, not getppid()
        env["RAY_TPU_NODE_PID"] = str(os.getpid())
        import ray_tpu
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        extra_paths = [pkg_parent, os.getcwd()]
        existing = os.environ.get("PYTHONPATH", "")
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in extra_paths
            + ([existing] if existing else []) if p)
        return env

    def _start_zygote(self) -> None:
        """Lazy: launched by the first worker spawn, not node start — a
        many-node virtual cluster (cluster_utils envelope) would
        otherwise pay one zygote interpreter boot per node up front
        (measured: 2x slower node join)."""
        if self._zygote_started:
            return
        self._zygote_started = True
        if not getattr(self.config, "worker_zygote", True):
            return
        env = dict(os.environ)
        env.update(self._worker_base_env())
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        out = open(os.path.join(
            log_dir, f"zygote-{self.node_id.hex()[:12]}.out"), "ab")
        try:
            self._zygote = subprocess.Popen(
                [sys.executable, "-u", "-m", "ray_tpu.core.zygote",
                 self._zygote_sock, str(os.getpid())],
                env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True)
        except Exception:
            logger.exception("zygote failed to start; worker spawns "
                             "fall back to cold boots")
            self._zygote = None

    def _zygote_spawn(self, env: Dict[str, str],
                      log_path: str) -> Optional[int]:
        """Ask the zygote for a forked worker; returns its pid, or None
        when the zygote isn't usable (booting, dead, disabled). The
        zygote forks and moves on immediately; the pid arrives from the
        CHILD once it is first scheduled — so this call can wait a
        while under load and must only run on spawner threads."""
        z = self._zygote
        if z is None or z.poll() is not None:
            return None
        import json as _json
        import socket as _socket
        try:
            conn = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            conn.settimeout(30.0)
            try:
                conn.connect(self._zygote_sock)
                conn.sendall((_json.dumps(
                    {"env": env, "log_path": log_path})
                    + "\n").encode())
                data = b""
                while not data.endswith(b"\n"):
                    chunk = conn.recv(4096)
                    if not chunk:
                        return None
                    data += chunk
            finally:
                conn.close()
            return int(_json.loads(data)["pid"])
        except Exception:
            return None

    def start(self) -> None:
        self._register_with_controller()
        for t in (threading.Thread(target=self._loop, name="node-loop", daemon=True),
                  threading.Thread(target=self._heartbeat_loop, name="node-hb", daemon=True),
                  threading.Thread(target=self._reaper_loop, name="node-reaper", daemon=True),
                  threading.Thread(target=self._memory_monitor_loop,
                                   name="node-memmon", daemon=True)):
            t.start()
            self._threads.append(t)
        for _ in range(self.num_initial_workers):
            self._start_worker(requested=False)

    def stop(self) -> None:
        self._stopped.set()
        if self._reliable is not None:
            self._reliable.stop()
        with self._workers_lock:
            procs = list(self.workers.values())
        for p in procs:
            try:
                p.terminate()
            except Exception:
                pass
        deadline = time.monotonic() + 3
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                try:
                    p.kill()
                except Exception:
                    pass
        if self._zygote is not None:
            try:
                self._zygote.terminate()
                self._zygote.wait(timeout=2)
            except Exception:
                try:
                    self._zygote.kill()
                except Exception:
                    pass
            try:
                os.unlink(self._zygote_sock)
            except OSError:
                pass
        try:
            self.sock.close(0)
            self.direct_sock.close(0)
            for s in self._peer_socks.values():
                s.close(0)
            self._peer_socks.clear()
        except Exception:
            pass
        self.shm.close()
        self.store.destroy()

    def _reliable_resend(self, target, mtype: bytes, payload) -> None:
        """Retransmit hook (reliable-layer thread): controller-bound
        messages re-enter _send (chaos filter re-applied; the stamp is
        idempotent); direct-channel resends park for the loop thread."""
        if self._stopped.is_set():
            return
        if target is None:
            self._send(mtype, payload)
        else:
            self._chaos_delayed.append((target, mtype, payload))

    def _reliable_ack(self, route, payload) -> None:
        if self._stopped.is_set():
            return
        if route is None:
            self._send(P.MSG_ACK, payload)
        else:
            self._chaos_delayed.append((route, P.MSG_ACK, payload))

    def _send(self, mtype: bytes, payload) -> None:
        if self._reliable is not None:
            payload = self._reliable.stamp(None, mtype, payload)
        if self._chaos is not None:
            for delay_s, pl in self._chaos.plan_send(None, mtype, payload):
                if delay_s > 0.0:
                    t = threading.Timer(delay_s, self._send_now,
                                        args=(mtype, pl))
                    t.daemon = True
                    t.start()
                else:
                    self._send_now(mtype, pl)
            return
        self._send_now(mtype, payload)

    def _send_now(self, mtype: bytes, payload) -> None:
        with self._send_lock:
            self.sock.send_multipart([mtype, P.dumps(payload)])

    # ------------------------------------------------------------ messages
    def _loop(self) -> None:
        poller = zmq.Poller()
        poller.register(self.sock, zmq.POLLIN)
        poller.register(self.direct_sock, zmq.POLLIN)
        while not self._stopped.is_set():
            try:
                events = dict(poller.poll(timeout=1000))
            except zmq.ZMQError:
                break
            while self._pull_retries:
                requester, m = self._pull_retries.popleft()
                try:
                    self._start_stream(requester, m)
                except Exception:
                    logger.exception("pull retry failed")
            while self._chaos_delayed:
                # chaos-delayed direct sends: already stamped/planned —
                # ship as-is from the loop thread that owns peer sockets
                target, mtype, pl = self._chaos_delayed.popleft()
                try:
                    self._peer_sock(target).send_multipart(
                        [mtype, P.dumps(pl)])
                except Exception:
                    pass
            if self.sock in events:
                while True:
                    try:
                        frames = self.sock.recv_multipart(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
                    try:
                        self._handle(frames[0], P.loads(frames[1]))
                    except Exception:
                        logger.exception("node: error handling %s", frames[0])
            if self.direct_sock in events:
                while True:
                    try:
                        frames = self.direct_sock.recv_multipart(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
                    try:
                        # [sender identity, mtype, payload]
                        self._handle_direct(frames[0], frames[1],
                                            P.loads(frames[2]))
                    except Exception:
                        logger.exception("node: error in direct %s",
                                         frames[1])
            self._check_pull_timeouts()

    def _peer_sock(self, target: bytes) -> "zmq.Socket":
        """Loop-thread-only: lazily connected DEALER to a peer node's
        direct ROUTER."""
        s = self._peer_socks.get(target)
        if s is None:
            s = self.ctx.socket(zmq.DEALER)
            s.setsockopt(zmq.IDENTITY, self.identity)
            s.setsockopt(zmq.LINGER, 0)
            s.setsockopt(zmq.SNDHWM, 0)
            s.connect(D.direct_addr(self.session_dir, target))
            self._peer_socks[target] = s
        self._peer_last_used[target] = time.monotonic()
        return s

    def _send_direct(self, target: bytes, mtype: bytes, payload) -> None:
        if self._chaos is not None:
            for delay_s, pl in self._chaos.plan_send(target, mtype,
                                                     payload):
                if delay_s > 0.0:
                    # peer sockets are loop-thread-only: the timer parks
                    # the send; the loop drains it on its next wakeup
                    t = threading.Timer(
                        delay_s, self._chaos_delayed.append,
                        args=((target, mtype, pl),))
                    t.daemon = True
                    t.start()
                else:
                    self._peer_sock(target).send_multipart(
                        [mtype, P.dumps(pl)])
            return
        self._peer_sock(target).send_multipart([mtype, P.dumps(payload)])

    def _prune_peer_socks(self, idle_s: float = 120.0) -> None:
        now = time.monotonic()
        for target in [t for t, used in self._peer_last_used.items()
                       if now - used > idle_s]:
            self._peer_last_used.pop(target, None)
            s = self._peer_socks.pop(target, None)
            if s is not None:
                try:
                    s.close(0)
                except Exception:
                    pass

    def _handle(self, mtype: bytes, m: dict) -> None:
        if self._chaos_dedup is not None and CH.check_dedup(
                self._chaos_dedup, m):
            return  # injected duplicate of a message already handled
        if self._reliable is not None:
            if mtype == P.MSG_ACK:
                self._reliable.on_ack(m)
                return
            if self._reliable.on_receive(None, m):
                return  # retransmit duplicate of a handled message
        if mtype == P.MSG_BATCH:
            for sub_type, sub_payload in m["msgs"]:
                try:
                    self._handle(sub_type, sub_payload)
                except Exception:
                    logger.exception("node: error in batched %s", sub_type)
            return
        if mtype == P.TASK_ASSIGN:
            if m.get("start_worker"):
                self._start_worker(requested=True)
        elif mtype == P.FREE_OBJECT:
            oid = ObjectID(m["object_id"])
            self.shm.release(oid)
            self.store.delete(oid)
        elif mtype == P.LOCATE_OBJECT:
            # directory-repair probe: a producer died before its
            # TASK_DONE reported this object, but the bytes are here
            oid = ObjectID(m["object_id"])
            if self.store.contains(oid):
                state, _, size = self.store.seg.lookup(oid) \
                    if hasattr(self.store, "seg") else (2, 0, 0)
                self._send(P.PUT_OBJECT, {
                    "object_id": m["object_id"],
                    "node_id": self.node_id.binary(),
                    "size": size})
        elif mtype == P.PULL_OBJECT:
            self._enqueue_pull(m)
        elif mtype == P.CANCEL_TASK:
            pid = m.get("pid")
            if pid:
                try:
                    os.kill(pid, signal.SIGKILL if m.get("force") else signal.SIGINT)
                except ProcessLookupError:
                    pass
        elif mtype == P.KILL_ACTOR:
            pid = m.get("pid")
            if pid:
                try:
                    os.kill(pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        elif mtype == P.WORKER_PINNED:
            self._pinned_workers.add(m["worker_identity"])
        elif mtype == P.RECONNECT:
            # controller restarted: re-announce this node + its objects,
            # and relay to our workers over their direct channels (the
            # fresh ROUTER cannot address them until they speak first)
            self._register_with_controller()
            with self._workers_lock:
                worker_ids = list(self.workers.keys())
            for wid in worker_ids:
                try:
                    self._send_direct(wid, P.RECONNECT, {})
                except Exception:
                    pass
        elif mtype == P.SHUTDOWN:
            self._stopped.set()

    # ------------------------------------------------------------- workers
    def _start_worker(self, requested: bool = True) -> None:
        """Queue a worker spawn for the spawner threads — the zygote
        handshake waits for the forked child's first schedule, which
        must never stall the caller (message loop / heartbeat)."""
        with self._spawn_init_lock:
            # main thread (initial workers) and node-loop thread
            # (controller TASK_ASSIGN) race here on first spawn
            self._spawn_count += 1
            if not self._spawner_threads:
                for i in range(4):
                    t = threading.Thread(target=self._spawner_loop,
                                         name=f"node-spawner-{i}",
                                         daemon=True)
                    t.start()
                    self._spawner_threads.append(t)
            if self._spawn_count > self.num_initial_workers + 2:
                # demand outgrew the initial pool (an actor burst or a
                # scale-up): the warm factory pays for itself from here.
                # Small clusters (most tests) never boot it — the first
                # few spawns use the cold path either way while the
                # zygote warms up.
                self._start_zygote()
            spawn_idx = self._spawn_count
        self._spawn_q.put((requested, spawn_idx))

    def _spawner_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                requested, spawn_idx = self._spawn_q.get(timeout=1.0)
            except Exception:
                continue
            try:
                self._spawn_one(requested, spawn_idx)
            except Exception:
                logger.exception("worker spawn failed")

    def _spawn_one(self, requested: bool, spawn_idx: int = 0) -> None:
        worker_id = WorkerID.from_random()
        delta = self._worker_base_env()
        delta["RAY_TPU_WORKER_ID"] = worker_id.hex()
        if self._chaos is not None:
            # stable chaos stream id: the Nth worker this node spawns
            # draws the same fault decisions on every replay (worker
            # ids are random and would de-correlate seeds)
            delta[CH.ENV_STREAM_ID] = str(spawn_idx)
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(
            log_dir, f"worker-{worker_id.hex()[:12]}.out")
        # warm path: fork from the zygote (~ms). Cold fallback: full
        # interpreter boot (zygote still starting, crashed, or disabled)
        pid = self._zygote_spawn(delta, log_path)
        if pid is not None:
            proc = _ForkedWorker(pid)
        else:
            env = dict(os.environ)
            env.update(delta)
            out = open(log_path, "ab")
            proc = subprocess.Popen(
                [sys.executable, "-u", "-m", "ray_tpu.core.worker"],
                env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True)
        with self._workers_lock:
            self.workers[worker_id.binary()] = proc
            self._worker_started[worker_id.binary()] = time.monotonic()
            if requested:
                # controller-requested: its starting_workers count must be
                # repaired if this worker dies before registering
                self._requested_workers.add(worker_id.binary())

    def _reaper_loop(self) -> None:
        while not self._stopped.wait(0.5):
            dead = []
            with self._workers_lock:
                for identity, proc in list(self.workers.items()):
                    if proc.poll() is not None:
                        dead.append(identity)
                        del self.workers[identity]
                        self._worker_started.pop(identity, None)
                        self._pinned_workers.discard(identity)
            for identity in dead:
                self._send(P.WORKER_EXIT, {
                    "worker_identity": identity,
                    "node_id": self.node_id.binary(),
                    "requested": identity in self._requested_workers,
                    "reason": "oom"
                    if self._oom_killed.pop(identity, False) else None})
                self._requested_workers.discard(identity)

    # ------------------------------------------------------- OOM defense
    def _memory_fraction(self) -> Optional[float]:
        try:
            import psutil
            return psutil.virtual_memory().percent / 100.0
        except Exception:
            return None

    def _memory_monitor_loop(self) -> None:
        """Reference: MemoryMonitor (memory_monitor.h:52) polls node
        usage; above the threshold a worker is killed by policy. The
        policy here is the reference's LIFO heuristic
        (worker_killing_policy.h:34 — newest-started worker loses the
        least progress; its task is failed as retriable OOM so the
        scheduler can re-run it when pressure clears)."""
        threshold = self.config.memory_usage_threshold
        if threshold <= 0:
            return
        try:
            import psutil  # noqa: F401
        except ImportError:
            logger.warning("psutil unavailable: OOM defense disabled")
            return
        period = self.config.memory_monitor_refresh_ms / 1000.0
        breaches = 0
        while not self._stopped.wait(period):
            frac = self._memory_fraction()
            if frac is None:
                continue  # transient read failure; keep monitoring
            if frac <= threshold:
                breaches = 0
                continue
            breaches += 1
            if breaches < self.config.memory_monitor_breaches:
                continue
            breaches = 0
            self._kill_one_worker_for_oom(frac)

    def _kill_one_worker_for_oom(self, frac: float) -> None:
        now = time.monotonic()
        with self._workers_lock:
            # workers still booting (interpreter + imports take seconds)
            # haven't had a chance to take work — killing them reclaims
            # nothing and can starve the cluster into never executing
            # anything
            candidates = [w for w in self.workers
                          if now - self._worker_started.get(w, now) > 5.0]
            if not candidates:
                return
            # stateless task workers go before actor hosts (reference:
            # worker_killing_policy prefers retriable work — killing an
            # actor loses its state for the same reclaimed bytes)
            task_workers = [w for w in candidates
                            if w not in self._pinned_workers]
            pool = task_workers or candidates

            def rss(w):
                try:
                    import psutil
                    return psutil.Process(self.workers[w].pid) \
                        .memory_info().rss
                except Exception:
                    return 0
            # newest first in 10s buckets (loses least progress), actual
            # RSS breaking ties toward the memory hog
            victim = max(pool, key=lambda w: (
                int(self._worker_started.get(w, 0.0) // 10), rss(w)))
            proc = self.workers[victim]
            self._oom_killed[victim] = True
        logger.warning(
            "memory usage %.0f%% above threshold %.0f%%: killing newest "
            "worker %s (pid %s)", frac * 100,
            self.config.memory_usage_threshold * 100,
            victim[:6].hex(), proc.pid)
        try:
            proc.kill()
        except Exception:
            pass

    def _collect_process_stats(self) -> list:
        """Per-process CPU/RSS of this node's workers + the node manager
        itself (reference: dashboard/modules/reporter/reporter_agent.py
        publishes per-process psutil stats from every node). psutil's
        cpu_percent needs a persistent Process handle between calls, so
        handles are cached by pid."""
        try:
            import psutil
        except ImportError:
            return []
        cache = self._psutil_cache
        with self._workers_lock:
            entries = [(w.hex(), "worker", p.pid)
                       for w, p in self.workers.items()
                       if p.poll() is None]
        entries.append(("", "node_manager", os.getpid()))
        out = []
        for ident, kind, pid in entries:
            try:
                pr = cache.get(pid)
                if pr is None:
                    pr = cache[pid] = psutil.Process(pid)
                    pr.cpu_percent(interval=None)  # prime the counter
                mi = pr.memory_info()
                out.append({
                    "worker_id": ident, "kind": kind, "pid": pid,
                    "cpu_percent": pr.cpu_percent(interval=None),
                    "rss": mi.rss,
                    "num_threads": pr.num_threads(),
                })
            except Exception:
                cache.pop(pid, None)
        for pid in [p for p in cache
                    if p not in {e[2] for e in entries}]:
            del cache[pid]
        return out

    def _heartbeat_loop(self) -> None:
        period = self.config.health_check_period_ms / 1000.0
        beat = 0
        while not self._stopped.wait(period):
            beat += 1
            # Native store: reclaim read-references held by dead PIDs
            # (plasma's disconnected-client cleanup).
            reap = getattr(self.store, "reap_dead_readers", None)
            if reap is not None:
                try:
                    reap()
                except Exception:
                    pass
            # background spill/eviction toward the budget: local creates
            # never notify this authority, so without a periodic sweep
            # the segment drifts to its physical ceiling and every
            # foreground create stalls behind a make_room RPC
            try:
                self.store.maybe_evict()
            except Exception:
                pass
            stats = self.store.stats()
            try:
                import psutil
                stats["mem_percent"] = psutil.virtual_memory().percent
            except Exception:
                pass
            if beat % 5 == 0:
                # per-process stats every 5th beat: psutil walks /proc,
                # which is too costly for the 1s heartbeat itself
                try:
                    stats["processes"] = self._collect_process_stats()
                except Exception:
                    pass
            try:
                from ray_tpu.core.metric_defs import update_from_state
                update_from_state(store_stats=stats, node_stats=stats)
            except Exception:
                pass
            self._send(P.HEARTBEAT, {
                "node_id": self.node_id.binary(), "stats": stats})
            self.recorder.maybe_flush()
            self.metrics_reporter.maybe_report()

    # ----------------------------------------------------------- transfers
    # Receiving side drives (reference: pull_manager.h:52 — the puller
    # admits work against a byte budget); the controller only names the
    # source. Chunks ride the direct node-to-node channel.
    def _handle_direct(self, sender: bytes, mtype: bytes, m: dict) -> None:
        if self._chaos_dedup is not None and CH.check_dedup(
                self._chaos_dedup, m):
            return  # injected duplicate of a message already handled
        if self._reliable is not None:
            if mtype == P.MSG_ACK:
                self._reliable.on_ack(m)
                return
            if self._reliable.on_receive(sender, m):
                return
        if mtype == P.MSG_BATCH:
            # a peer's flusher can coalesce several direct messages
            # (e.g. concurrent STORE_RPCs) into one batch frame
            for sub_type, sub_payload in m["msgs"]:
                try:
                    self._handle_direct(sender, sub_type, sub_payload)
                except Exception:
                    logger.exception("node: error in batched direct %s",
                                     sub_type)
            return
        if mtype == P.STORE_RPC:
            # spill/restore move megabytes through disk: never on the
            # message loop (it also carries heartbeats and transfers).
            # One long-lived maintenance thread drains these — under
            # store pressure every blocked worker polls frequently, and
            # a thread per request would churn exactly then.
            if self._store_rpc_thread is None:
                self._store_rpc_thread = threading.Thread(
                    target=self._store_rpc_loop, name="node-store-rpc",
                    daemon=True)
                self._store_rpc_thread.start()
            self._store_rpc_q.put((sender, m))
        elif mtype == P.PULL_REQUEST:
            self._start_stream(sender, m)
        elif mtype == P.PUSH_OBJECT:
            self._receive_push(sender, m)
        elif mtype == P.CHUNK_ACK:
            self._on_chunk_ack(sender, m)
        elif mtype == P.PULL_FAILED:
            # the SOURCE says the object is gone there: stale location
            self._pull_failed(m["object_id"], m.get("src_node"),
                              stale_src=True)

    def _requeue_pull_request(self, requester: bytes, m: dict) -> None:
        # timer thread: park the retry; the message loop drains it on
        # its next wakeup (loop thread owns all stream/peer state)
        self._pull_retries.append((requester, m))

    def _store_rpc_loop(self) -> None:
        #: reply sockets cached per sender (this thread only)
        reply_socks: Dict[bytes, zmq.Socket] = {}
        while not self._stopped.is_set():
            try:
                sender, m = self._store_rpc_q.get(timeout=1.0)
            except Exception:
                continue
            try:
                self._store_rpc(sender, m, reply_socks)
            except Exception:
                logger.exception("store rpc failed")

    def _store_rpc(self, sender: bytes, m: dict,
                   reply_socks: Optional[Dict[bytes, "zmq.Socket"]]
                   = None) -> None:
        """Worker-requested store maintenance (reference: plasma's
        create-request queue + spilled-object restore requests run in
        the store owner, not the client)."""
        op = m.get("op")
        out: dict = {}
        try:
            if op == "make_room":
                out["freed"] = self.store.make_room(
                    int(m.get("bytes", 0)))
            elif op == "restore":
                oid = ObjectID(m["object_id"])
                try:
                    result = self.store.maybe_restore(
                        oid, for_pid=m.get("pid"))
                except TypeError:
                    # python-store fallback without lease support
                    result = self.store.maybe_restore(oid)
                out["ok"] = result is True
                out["leased"] = result is True and bool(m.get("pid")) \
                    and hasattr(self.store, "seg")
                # capacity-full restores are transient (see
                # NativeShmStore.maybe_restore): tell the caller to
                # retry instead of giving up
                out["retry"] = result == "retry"
                if result == "lost":
                    # the local backing copy is unusable (disk faults /
                    # truncation): report ourselves as a stale holder so
                    # the controller prunes the location and re-pulls
                    # from another holder / reconstructs via lineage
                    self._send(P.PULL_FAILED, {
                        "object_id": m["object_id"],
                        "src_node": self.node_id.binary(),
                        "stale_src": True})
            else:
                out["error"] = f"unknown store op {op!r}"
        except Exception as e:  # noqa: BLE001
            out["error"] = str(e)
        # maintenance thread (not the message loop): _peer_socks is
        # loop-thread-only, so reply over this thread's own cached
        # DEALER per sender. Unique identity: reusing the node's fixed
        # identity would collide with its persistent DEALER to the same
        # worker ROUTER and the reply would be silently dropped.
        sock = None if reply_socks is None else reply_socks.get(sender)
        if sock is None:
            sock = self.ctx.socket(zmq.DEALER)
            sock.setsockopt(zmq.IDENTITY,
                            self.identity[:8] + os.urandom(8))
            sock.setsockopt(zmq.LINGER, 1000)
            sock.connect(D.direct_addr(self.session_dir, sender))
            if reply_socks is not None:
                reply_socks[sender] = sock
                while len(reply_socks) > 256:
                    old, s_old = next(iter(reply_socks.items()))
                    del reply_socks[old]
                    s_old.close(0)
        try:
            sock.send_multipart([P.GENERIC_REPLY, P.dumps(
                {"rid": m.get("rid"), "data": out})])
        finally:
            if reply_socks is None:
                sock.close()

    def _enqueue_pull(self, m: dict) -> None:
        b = m["object_id"]
        if b in self._pulling or self.store.contains(ObjectID(b)):
            return
        self._pull_queue.append(m)
        self._drain_pull_queue()

    def _drain_pull_queue(self) -> None:
        budget = self.config.max_inflight_pull_bytes
        while self._pull_queue:
            m = self._pull_queue[0]
            size = max(1, int(m.get("size") or 1))
            if self._pulling and \
                    self._pull_bytes_inflight + size > budget:
                return  # admission: wait for an in-flight pull to finish
            self._pull_queue.pop(0)
            b = m["object_id"]
            if b in self._pulling or self.store.contains(ObjectID(b)):
                continue
            self._pulling[b] = {
                "src_identity": m["src_identity"], "src_node": m.get("src_node"),
                "size": size, "deadline": time.monotonic() +
                self.config.pull_timeout_s}
            self._pull_bytes_inflight += size
            self._send_direct(m["src_identity"], P.PULL_REQUEST,
                              {"object_id": b})

    def _finish_pull(self, b: bytes) -> None:
        st = self._pulling.pop(b, None)
        if st is not None:
            self._pull_bytes_inflight -= st["size"]
        self._drain_pull_queue()

    def _abort_incoming(self, b: bytes) -> None:
        """Drop a partial in-flight assembly so a later retry can create
        the allocation afresh (a half-written unsealed extent would make
        every retry fail at shm.create)."""
        st = self._incoming.pop(b, None)
        if st is not None:
            oid = ObjectID(b)
            try:
                self.shm.release(oid)
            except Exception:
                pass
            try:
                self.shm.delete(oid)
            except Exception:
                pass
            try:
                self.store.delete(oid)
            except Exception:
                pass

    def _pull_failed(self, b: bytes, src_node, stale_src: bool) -> None:
        if b not in self._pulling and b not in self._incoming:
            return  # late failure for a pull already finished/aborted
        self._abort_incoming(b)
        self._finish_pull(b)
        # stale_src=True only when the SOURCE reported the object missing;
        # dest-local causes (timeout, store pressure) must not make the
        # controller discard a perfectly good holder
        self._send(P.PULL_FAILED, {"object_id": b, "src_node": src_node,
                                   "stale_src": stale_src})

    def _check_pull_timeouts(self) -> None:
        now = time.monotonic()
        if self._pulling:
            for b, st in list(self._pulling.items()):
                if now > st["deadline"]:
                    logger.warning("pull of %s timed out",
                                   ObjectID(b).hex()[:12])
                    self._pull_failed(b, st.get("src_node"),
                                      stale_src=False)
        if self._outgoing:
            for key, st in list(self._outgoing.items()):
                if now - st["last_activity"] > self.config.pull_timeout_s:
                    self._close_stream(key)
        self._prune_peer_socks()

    # Source side (reference: ObjectManager::Push): windowed streaming —
    # at most stream_window_chunks unacked chunks per stream, so a huge
    # object never sits fully buffered in the sender's zmq queue and the
    # loop thread is never blocked for the whole object.
    def _start_stream(self, requester: bytes, m: dict) -> None:
        b = m["object_id"]
        oid = ObjectID(b)
        restored = self.store.maybe_restore(oid)
        view = self.shm.get_view(oid, timeout=2.0) \
            if restored is True else None
        if view is None and restored == "retry" and \
                m.get("_restore_tries", 0) < 20:
            # transient capacity pressure (segment full of reader-held
            # extents): the on-disk copy EXISTS — reporting PULL_FAILED
            # would make the controller drop the only holder. Re-try
            # shortly instead (off-loop timer; the message loop must
            # not sleep).
            m = dict(m, _restore_tries=m.get("_restore_tries", 0) + 1)
            t = threading.Timer(0.5, self._requeue_pull_request,
                                args=(requester, m))
            t.daemon = True
            t.start()
            return
        if view is None:
            logger.warning("pull for missing object %s", oid.hex()[:12])
            self._send_direct(requester, P.PULL_FAILED, {
                "object_id": b, "src_node": self.node_id.binary()})
            return
        chunk = self.config.transfer_chunk_bytes
        total = len(view)
        st = {
            "oid": oid, "view": view, "total": total,
            "nchunks": max(1, (total + chunk - 1) // chunk),
            "next_seq": 0, "unacked": 0,
            "last_activity": time.monotonic(),
        }
        self._outgoing[(requester, b)] = st
        self._pump_stream(requester, b, st)

    def _pump_stream(self, requester: bytes, b: bytes, st: dict) -> None:
        chunk = self.config.transfer_chunk_bytes
        window = self.config.stream_window_chunks
        while st["next_seq"] < st["nchunks"] and st["unacked"] < window:
            i = st["next_seq"]
            part = bytes(st["view"][i * chunk:(i + 1) * chunk])
            self._send_direct(requester, P.PUSH_OBJECT, {
                "object_id": b, "seq": i, "nchunks": st["nchunks"],
                "total": st["total"], "data": part})
            st["next_seq"] += 1
            st["unacked"] += 1
        st["last_activity"] = time.monotonic()
        if st["next_seq"] >= st["nchunks"] and st["unacked"] <= 0:
            self._close_stream((requester, b))

    def _on_chunk_ack(self, sender: bytes, m: dict) -> None:
        key = (sender, m["object_id"])
        st = self._outgoing.get(key)
        if st is None:
            return
        st["unacked"] -= m.get("n", 1)
        self._pump_stream(sender, m["object_id"], st)

    def _close_stream(self, key: tuple) -> None:
        st = self._outgoing.pop(key, None)
        if st is not None:
            self.shm.release(st["oid"])

    def _receive_push(self, sender: bytes, m: dict) -> None:
        """Destination side: assemble chunks, seal, announce location."""
        b = m["object_id"]
        oid = ObjectID(b)
        # flow control: ack regardless of outcome so the source's window
        # drains even for duplicate/late chunks
        self._send_direct(sender, P.CHUNK_ACK, {"object_id": b, "n": 1})
        if self.store.contains(oid):
            self._finish_pull(b)
            return
        pull = self._pulling.get(b)
        if pull is None or sender != pull["src_identity"]:
            # no active pull from this source (it timed out / was retried
            # from elsewhere): ignoring the chunk also prevents orphan
            # partial allocations nobody would ever complete
            return
        st = self._incoming.get(b)
        if st is None:
            view = self.shm.create(oid, m["total"])
            st = {"view": view, "seqs": set()}
            self._incoming[b] = st
        chunk = self.config.transfer_chunk_bytes
        off = m["seq"] * chunk
        data = m["data"]
        st["view"][off:off + len(data)] = data
        # distinct-seq tracking: duplicate deliveries (source retry after
        # a timeout race) must not count toward completion
        st["seqs"].add(m["seq"])
        if pull is not None:
            pull["deadline"] = time.monotonic() + self.config.pull_timeout_s
        if len(st["seqs"]) >= m["nchunks"]:
            self.shm.seal(oid)
            try:
                self.store.on_sealed(oid, m["total"], grace=True)
            except TypeError:
                self.store.on_sealed(oid, m["total"])
            del self._incoming[b]
            self._finish_pull(b)
            self._send(P.PUT_OBJECT, {
                "object_id": b, "node_id": self.node_id.binary(),
                "size": m["total"]})

    def run_forever(self) -> None:
        while not self._stopped.wait(0.5):
            pass
        self.stop()


def detect_resources(num_cpus: Optional[float] = None,
                     num_tpus: Optional[float] = None,
                     custom: Optional[Dict[str, float]] = None,
                     memory: Optional[int] = None) -> Dict[str, float]:
    """Build the node resource map (reference:
    ``python/ray/_private/resource_spec.py`` + accelerator detection)."""
    from ray_tpu.core.accelerators import tpu_chip_count, tpu_pod_type
    res: Dict[str, float] = {}
    res["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    if memory is None:
        try:
            import psutil
            memory = int(psutil.virtual_memory().total * 0.7)
        except Exception:
            memory = 4 << 30
    res["memory"] = float(memory)
    chips = num_tpus if num_tpus is not None else tpu_chip_count()
    if chips:
        res["TPU"] = float(chips)
        pod_type = tpu_pod_type()
        if pod_type and get_config().tpu_pod_head_resource:
            # reference: tpu.py:379-382 — one gang resource on slice host 0
            from ray_tpu.core.accelerators import tpu_worker_index
            if tpu_worker_index() == 0:
                res[f"TPU-{pod_type}-head"] = 1.0
    res.update(custom or {})
    return res


def main() -> None:
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--session-dir", required=True)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default="{}")
    p.add_argument("--labels", default="{}")
    p.add_argument("--initial-workers", type=int, default=0)
    p.add_argument("--node-id", default=None,
                   help="hex NodeID (autoscaler providers pre-assign one "
                        "to join provider inventory with cluster state)")
    args = p.parse_args()
    import json
    res = detect_resources(args.num_cpus, args.num_tpus,
                           json.loads(args.resources))
    nm = NodeManager(args.session_dir, res, labels=json.loads(args.labels),
                     node_id=NodeID.from_hex(args.node_id)
                     if args.node_id else None,
                     num_initial_workers=args.initial_workers)
    nm.start()
    nm.run_forever()


if __name__ == "__main__":
    main()
