"""Reliable-delivery sublayer for the control plane.

The reference runtime gets at-least-once control RPCs for free from gRPC
retries plus the raylet's lease/reconnect machinery; our ZeroMQ transport
has ordered per-peer delivery but NO retransmit — a dropped one-way
message (lossy link, injected fault, severed peer) used to be gone for
good, which is why chaos drops were restricted to message types with
bespoke recovery paths.

This module closes that gap for the critical one-way types
(:data:`RELIABLE_TYPES`): every such message is stamped with a per-process
wire sequence number, the receiver acks (batched ack *ranges* over a new
``MSG_ACK`` message, flushed within a few ms so they effectively
piggyback on existing traffic bursts), and the sender keeps an
unacked-ring that retransmits with jittered exponential backoff
(``ray_tpu/util/backoff.py``) until one of:

- an **ack** arrives (entry dropped from the ring),
- a **peer-death notice** (``drop_target`` — the higher layer already has
  a recovery story for dead peers: lease revocation, actor restart,
  worker-exit task failover),
- the **attempt cap**, which surfaces a typed
  :class:`~ray_tpu.exceptions.DeliveryFailedError` through the ``on_fail``
  hook (and the ``failures`` list) instead of losing the message silently.

Retransmits are made idempotent on the receive side by the same bounded
LRU dedup filter chaos duplication uses (:class:`chaos.SeqDeduper`): a
receiver that already handled ``(sender tag, seq)`` re-acks and drops the
replay, so delivery is at-least-once on the wire and exactly-once-effect
at the handler.

Ordering note: first transmissions keep zmq's per-peer FIFO; a
retransmit can arrive after younger traffic. Every handler of a reliable
type already tolerates reordering (the chaos delay fault injects exactly
this), and the one FIFO-sensitive path — compact actor-call templates —
self-heals via ``TMPL_MISS``.
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.util.backoff import backoff_delay

logger = logging.getLogger(__name__)

#: message types carried reliably: the one-way control messages whose
#: loss previously wedged the runtime (dispatch/assign/done/create) plus
#: the object-plane notifications whose loss cost expensive fallback
#: probes (PUT_OBJECT directory announcements, direct TASK_RESULT
#: pushes). Request/reply RPCs are NOT here — their loss already
#: surfaces as a typed RpcTimeoutError at the caller — except
#: CREATE_ACTOR, whose reply is cheap but whose request loss used to eat
#: the full RPC timeout.
RELIABLE_TYPES = frozenset({
    b"DSP",   # TASK_DISPATCH  controller/driver -> worker
    b"ACL",   # ACTOR_CALL     caller -> actor worker (direct)
    b"ASG",   # TASK_ASSIGN    controller -> node
    b"DON",   # TASK_DONE      worker -> controller
    b"CAC",   # CREATE_ACTOR   driver -> controller
    b"PUT",   # PUT_OBJECT     owner/node -> controller
    b"RES",   # TASK_RESULT    worker -> owner / controller -> owner
    b"SIT",   # STREAM_ITEM    worker -> owner (direct): a lost item
              # report would leave a permanent gap in the stream
    b"SEF",   # STREAM_EOF     worker -> owner (direct): loss would hang
              # the consumer's final next() forever
    b"SCR",   # STREAM_CREDIT  owner -> worker (direct): credits are
              # cumulative so a lost one is healed by the next — but the
              # LAST credit has no successor, and its loss would wedge
              # the producer at the backpressure window for good
    b"TEV",   # TASK_EVENTS    any -> controller: flight-recorder flush
              # (core/events.py). Dedup at the controller makes the
              # merged event stream exactly-once-effect like the
              # lifecycle messages it describes; the producer side
              # stays fire-and-forget (a flush never blocks a task)
    b"MRT",   # METRIC_REPORT  any -> controller: fleet metric snapshot
              # (core/metrics_plane.py). Same contract as TEV —
              # exactly-once-effect at the controller, fire-and-forget
              # for the producer; the reporter additionally abandons
              # superseded in-flight reports via drop_oldest_of (a
              # snapshot is cumulative, so only the newest matters)
    b"RSP",   # REQUEST_SPANS  any -> controller: per-request trace
              # span batch (serve/request_trace.py). Same contract as
              # TEV — exactly-once-effect at the controller (the store
              # additionally dedups by (request_id, part, seq) so a
              # chaos dup never doubles a waterfall), fire-and-forget
              # for the producer
})

#: payload key carrying ``(sender tag, seq)``; popped before handlers
STAMP = "__rseq__"


def _compress(seqs: List[int]) -> List[Tuple[int, int]]:
    """Sorted-unique seq list -> inclusive ``(lo, hi)`` ranges."""
    out: List[List[int]] = []
    for s in sorted(set(seqs)):
        if out and s == out[-1][1] + 1:
            out[-1][1] = s
        else:
            out.append([s, s])
    return [(a, b) for a, b in out]


class ReliableTransport:
    """Per-process ack/retransmit engine. One instance serves every link
    the process speaks on (controller DEALER + direct peer channels) —
    the ``resend``/``send_ack`` callbacks route by target.

    ``resend(target, mtype, payload)`` re-enqueues a message through the
    process's normal (thread-safe) send path; the payload is already
    stamped, so the transport-side ``stamp()`` hook must treat it as a
    pass-through. ``send_ack(route, payload)`` ships a ``MSG_ACK`` back
    over the link a stamped message arrived on (``route`` is whatever
    opaque key the receiver passed to :meth:`on_receive`).
    """

    def __init__(self, resend: Callable[[Any, bytes, dict], None],
                 send_ack: Callable[[Any, dict], None], *,
                 base_s: float = 0.25, cap_s: float = 5.0,
                 max_attempts: int = 12, ack_delay_s: float = 0.02,
                 types: frozenset = RELIABLE_TYPES,
                 rng=None, on_fail: Optional[Callable] = None,
                 name: str = "", start_thread: bool = True,
                 recorder=None):
        from ray_tpu.core.chaos import SeqDeduper
        self._resend = resend
        self._send_ack = send_ack
        self._base = base_s
        self._cap = cap_s
        self._max_attempts = max_attempts
        self._ack_delay = ack_delay_s
        self._types = types
        self._rng = rng
        self._on_fail = on_fail
        self.name = name
        #: flight recorder (core/events.py FlightRecorder) for
        #: RETRANSMIT / DUP_DROPPED / ACK_RTT / DELIVERY_FAILED events;
        #: None keeps every hook a single attribute check
        self.recorder = recorder
        self._metrics = None  # lazily-bound runtime metric handles

        #: unique per process *instance*: distinguishes sender streams at
        #: a receiver and fences stale acks across restarts
        self.tag = os.urandom(8)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        #: seq -> {target, mtype, payload, attempts, due, born}
        self._ring: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        #: route -> sender tag -> [seqs to ack]
        self._pending_acks: Dict[Any, Dict[bytes, List[int]]] = {}
        self._ack_first_at: Optional[float] = None
        self._dedup = SeqDeduper(cap=65536)
        self._stopped = threading.Event()
        self.stats: "collections.Counter" = collections.Counter()
        #: bounded log of messages given up on (typed errors)
        self.failures: List[BaseException] = []
        self._thread: Optional[threading.Thread] = None
        if start_thread:
            self._thread = threading.Thread(
                target=self._loop, name=f"{name or 'reliable'}-retx",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ sender
    def stamp(self, target: Any, mtype: bytes, payload: Any) -> Any:
        """Send-path hook: stamp a reliable message and record it in the
        unacked ring. Pass-through for non-reliable types, non-dict
        payloads, and already-stamped retransmits (their ring entry — and
        seq — must survive the resend)."""
        if mtype not in self._types or not isinstance(payload, dict) \
                or STAMP in payload:
            return payload
        now = time.monotonic()
        with self._cond:
            seq = next(self._seq)
            payload = dict(payload, **{STAMP: (self.tag, seq)})
            self._ring[seq] = {
                "target": target, "mtype": mtype, "payload": payload,
                "attempts": 0, "due": now + self._delay(0), "born": now}
            self.stats["sent"] += 1
            # per-type sent accounting: lets tests and postmortems
            # assert which paths rode reliable delivery (e.g. the disagg
            # KV hand-off's ACL calls) without scraping the ring
            self.stats["sent:" + mtype.decode("ascii", "replace")] += 1
            self._cond.notify()
        return payload

    def _m(self):
        """Lazily-bound runtime metric handles (import deferred: unit
        tests drive bare transports with no runtime around)."""
        m = self._metrics
        if m is None:
            from ray_tpu.core.metric_defs import runtime_metrics
            base = runtime_metrics()
            m = self._metrics = (
                base.retransmits,            # 0: Counter by type
                base.ack_rtt.bound(),        # 1: Histogram
                base.dup_dropped.bound(),    # 2: Counter
                base.delivery_failed.bound(),  # 3: Counter
                base.ack_batch_size.bound())   # 4: Histogram
        return m

    @staticmethod
    def _task_hex(payload) -> Optional[str]:
        tid = payload.get("task_id") if isinstance(payload, dict) else None
        return tid.hex() if isinstance(tid, bytes) else tid

    def _note_retransmit(self, mtype: bytes, payload: dict,
                         attempt: int) -> None:
        try:
            kind = mtype.decode("ascii", "replace")
            self._m()[0].inc(tags={"type": kind})
            if self.recorder is not None:
                self.recorder.record("RETRANSMIT", type=kind,
                                     attempt=attempt,
                                     task=self._task_hex(payload))
        except Exception:
            pass

    def _delay(self, attempt: int) -> float:
        # "equal" jitter keeps a floor of half the window: a retransmit
        # fired before the receiver's batched ack can possibly return is
        # a guaranteed duplicate
        return backoff_delay(attempt, self._base, self._cap,
                             jitter="equal", rng=self._rng)

    def on_ack(self, m: dict) -> None:
        """Handle an incoming ``MSG_ACK``: drop acked seqs from the ring.
        Acks stamped with another instance's tag (pre-restart traffic)
        are ignored."""
        now = time.monotonic()
        acked = []
        with self._cond:
            for tag, ranges in m.get("acks", ()):
                if tag != self.tag:
                    continue
                for lo, hi in ranges:
                    for seq in range(lo, hi + 1):
                        e = self._ring.pop(seq, None)
                        if e is not None:
                            self.stats["acked"] += 1
                            acked.append(e)
        for e in acked:
            # send-to-ack latency (retransmit attempts included): the
            # per-message delivery-health signal
            try:
                rtt = now - e["born"]
                self._m()[1].observe(rtt)
                if e["attempts"] > 0 and self.recorder is not None:
                    # only retransmitted messages are interesting enough
                    # to keep as events — a healthy ack would flood the
                    # ring with one event per message
                    self.recorder.record(
                        "ACK_RTT", rtt_s=round(rtt, 6),
                        attempts=e["attempts"],
                        type=e["mtype"].decode("ascii", "replace"),
                        task=self._task_hex(e["payload"]))
            except Exception:
                pass

    def drop_target(self, target: Any) -> int:
        """Peer-death notice: stop retransmitting to ``target`` (the
        higher layer owns recovery for dead peers). Returns the number of
        abandoned messages."""
        with self._cond:
            gone = [s for s, e in self._ring.items()
                    if e["target"] == target]
            for s in gone:
                del self._ring[s]
            self.stats["dropped_dead_peer"] += len(gone)
        return len(gone)

    @property
    def unacked(self) -> int:
        with self._lock:
            return len(self._ring)

    def drop_oldest_of(self, mtype: bytes, keep: int) -> int:
        """Abandon the OLDEST unacked in-flight messages of ``mtype``
        beyond ``keep`` newest. For supersedable periodic reports
        (METRIC_REPORT): a newer cumulative snapshot makes older ones
        worthless, so retransmitting them through an outage is pure
        backlog — the caller counts what it asked to drop. Returns the
        number abandoned."""
        with self._cond:
            seqs = [s for s, e in self._ring.items()
                    if e["mtype"] == mtype]
            n = len(seqs) - max(0, keep)
            if n <= 0:
                return 0
            for s in seqs[:n]:  # ring is seq-ordered: oldest first
                del self._ring[s]
            self.stats["dropped_superseded"] += n
            return n

    # ---------------------------------------------------------- receiver
    def on_receive(self, route: Any, payload: Any) -> bool:
        """Receive-path hook: pop the wire stamp, queue a batched ack
        back over ``route``, and return True when the payload is a
        retransmit duplicate that must be discarded (the ack is still
        queued — the original's ack may have been the loss)."""
        if not isinstance(payload, dict):
            return False
        key = payload.pop(STAMP, None)
        if key is None:
            return False
        tag, seq = key
        with self._cond:
            self._pending_acks.setdefault(route, {}) \
                .setdefault(tag, []).append(seq)
            if self._ack_first_at is None:
                self._ack_first_at = time.monotonic()
            self._cond.notify()
        if self._dedup.seen(key):
            self.stats["dup_dropped"] += 1
            try:
                self._m()[2].inc()
                if self.recorder is not None:
                    self.recorder.record(
                        "DUP_DROPPED", task=self._task_hex(payload))
            except Exception:
                pass
            return True
        return False

    # -------------------------------------------------------- the engine
    def flush_acks(self) -> None:
        """Ship every pending ack now (callable from any thread; the
        background loop also calls this on its timer)."""
        with self._cond:
            batches = self._take_acks_locked()
        self._ship_acks(batches)

    def _take_acks_locked(self) -> List[Tuple[Any, dict]]:
        if not self._pending_acks:
            return []
        pending, self._pending_acks = self._pending_acks, {}
        self._ack_first_at = None
        out = []
        for route, per_tag in pending.items():
            acks = [(tag, _compress(seqs))
                    for tag, seqs in per_tag.items()]
            out.append((route, {"acks": acks}))
        return out

    def _ship_acks(self, batches: List[Tuple[Any, dict]]) -> None:
        for route, payload in batches:
            try:
                self._send_ack(route, payload)
                self.stats["acks_sent"] += 1
            except Exception:
                logger.exception("%s: ack send failed", self.name)
                continue
            try:
                self._m()[4].observe(sum(
                    hi - lo + 1 for _, ranges in payload["acks"]
                    for lo, hi in ranges))
            except Exception:
                pass

    def _collect_due_locked(self, now: float):
        resends, failures = [], []
        for seq in list(self._ring):
            e = self._ring[seq]
            if e["due"] > now:
                continue
            e["attempts"] += 1
            if e["attempts"] > self._max_attempts:
                del self._ring[seq]
                from ray_tpu.exceptions import DeliveryFailedError
                failures.append(DeliveryFailedError(
                    e["mtype"], target=e["target"],
                    attempts=e["attempts"] - 1,
                    elapsed_s=now - e["born"]))
                continue
            e["due"] = now + self._delay(e["attempts"])
            resends.append((e["target"], e["mtype"], e["payload"],
                            e["attempts"]))
        return resends, failures

    def _note_failure(self, err) -> None:
        try:
            self._m()[3].inc()
            if self.recorder is not None:
                self.recorder.record("DELIVERY_FAILED", error=str(err))
        except Exception:
            pass

    def _next_wake_locked(self, now: float) -> Optional[float]:
        wake = None
        if self._ring:
            wake = min(e["due"] for e in self._ring.values())
        if self._ack_first_at is not None:
            ack_at = self._ack_first_at + self._ack_delay
            wake = ack_at if wake is None else min(wake, ack_at)
        if wake is None:
            return None
        return max(0.0, wake - now)

    def _loop(self) -> None:
        while not self._stopped.is_set():
            with self._cond:
                self._cond.wait(self._next_wake_locked(time.monotonic()))
                if self._stopped.is_set():
                    return
                now = time.monotonic()
                resends, failures = self._collect_due_locked(now)
                acks = []
                if self._ack_first_at is not None and \
                        now >= self._ack_first_at + self._ack_delay:
                    acks = self._take_acks_locked()
            self._ship_acks(acks)
            for target, mtype, payload, attempt in resends:
                self.stats["retransmit"] += 1
                self._note_retransmit(mtype, payload, attempt)
                try:
                    self._resend(target, mtype, payload)
                except Exception:
                    logger.exception("%s: retransmit of %s failed",
                                     self.name, mtype)
            for err in failures:
                self.stats["delivery_failed"] += 1
                self._note_failure(err)
                if len(self.failures) < 256:
                    self.failures.append(err)
                logger.error("%s: %s", self.name, err)
                if self._on_fail is not None:
                    try:
                        self._on_fail(err)
                    except Exception:
                        logger.exception("%s: on_fail hook failed",
                                         self.name)

    def step(self, now: Optional[float] = None) -> None:
        """Single-threaded driver for tests (``start_thread=False``):
        run one retransmit/ack pass at ``now``."""
        if now is None:
            now = time.monotonic()
        with self._cond:
            resends, failures = self._collect_due_locked(now)
            acks = self._take_acks_locked()
        self._ship_acks(acks)
        for target, mtype, payload, attempt in resends:
            self.stats["retransmit"] += 1
            self._note_retransmit(mtype, payload, attempt)
            self._resend(target, mtype, payload)
        for err in failures:
            self.stats["delivery_failed"] += 1
            self._note_failure(err)
            if len(self.failures) < 256:
                self.failures.append(err)
            if self._on_fail is not None:
                self._on_fail(err)

    def stop(self) -> None:
        self._stopped.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def maybe_transport(config, resend, send_ack, *, rng=None,
                    on_fail=None, name: str = "",
                    recorder=None) -> Optional[ReliableTransport]:
    """Build the process's transport from config; None when the layer is
    disabled (``RAY_TPU_RELIABLE_DELIVERY=0``) so every hook stays a
    single attribute check."""
    if not getattr(config, "reliable_delivery", True):
        return None
    return ReliableTransport(
        resend, send_ack,
        base_s=getattr(config, "retransmit_base_s", 0.25),
        cap_s=getattr(config, "retransmit_cap_s", 5.0),
        max_attempts=getattr(config, "retransmit_max_attempts", 12),
        ack_delay_s=getattr(config, "ack_flush_delay_s", 0.02),
        rng=rng, on_fail=on_fail, name=name, recorder=recorder)
