"""Native-segment store/client: same API as ShmObjectStore/ShmClient,
backed by the C++ single-segment allocator (``ray_tpu/_native``).

One ``/dev/shm/<session>.seg`` holds the index + all object bytes, so
create/seal/contains are shared-memory operations instead of per-object
``open``/``ftruncate``/``mmap`` syscalls (the plasma property —
``plasma_allocator.h`` — that the pure-Python store approximates with
one file per object). Eviction/spill policy stays in the Python store
class: the segment is the data plane.
"""

from __future__ import annotations

import ctypes
import errno
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

import logging

from ray_tpu.core.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

logger = logging.getLogger(__name__)

#: RAY_TPU_STORE_DEBUG=1 logs object lifecycle decisions with full ids
#: (spill/restore/delete forensics; analog of plasma's debug-level
#: object-lifecycle logging)
STORE_DEBUG = os.environ.get("RAY_TPU_STORE_DEBUG") == "1"

_SHM_ROOT = "/dev/shm"
_FULL = 2 ** 64 - 1
_EXISTS = 2 ** 64 - 2

_PAGE = 4096
_MADV_POPULATE_WRITE = 23  # linux 5.14+: prefault + PTE setup in one call
_libc = ctypes.CDLL(None, use_errno=True)


def _madvise_populate(base: int, off: int, size: int) -> None:
    """Fault a range in eagerly. Writing through fresh tmpfs pages costs a
    trap per 4 KiB (~5x bandwidth loss measured); one madvise populates the
    range at kernel speed. On resident pages it only fills PTEs (cheap), so
    this is safe to call on every create. Errors (old kernels) are
    ignored — the copy then faults lazily as before."""
    start = (base + off) & ~(_PAGE - 1)
    end = base + off + size
    try:
        _libc.madvise(ctypes.c_void_p(start),
                      ctypes.c_size_t(end - start), _MADV_POPULATE_WRITE)
    except Exception:
        pass


def _seg_path(session_name: str) -> str:
    return os.path.join(_SHM_ROOT, f"{session_name}.seg")


class _Segment:
    """One mapped native segment (create or open)."""

    def __init__(self, lib, session_name: str,
                 capacity: Optional[int] = None, nslots: int = 65536):
        self.lib = lib
        self.path = _seg_path(session_name)
        if capacity is not None:
            self.handle = lib.ns_create(
                self.path.encode(), capacity, nslots)
            self.owner = True
        else:
            self.handle = lib.ns_open(self.path.encode())
            self.owner = False
        if not self.handle:
            raise OSError(f"cannot map native segment {self.path}")
        total = lib.ns_total_size(self.handle)
        base = lib.ns_base(self.handle)
        self.base = base
        self.total = total
        self._buf = (ctypes.c_char * total).from_address(base)
        self.view = memoryview(self._buf).cast("B")

    def alloc(self, oid: ObjectID, size: int) -> int:
        return self.lib.ns_alloc(self.handle, oid.binary(), size)

    def seal(self, oid: ObjectID) -> int:
        return self.lib.ns_seal(self.handle, oid.binary())

    def lookup(self, oid: ObjectID):
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        state = self.lib.ns_lookup(
            self.handle, oid.binary(), ctypes.byref(off),
            ctypes.byref(size))
        return state, off.value, size.value

    def delete(self, oid: ObjectID) -> int:
        return self.lib.ns_delete(self.handle, oid.binary())

    def evict(self, oid: ObjectID) -> int:
        """Free only if unreferenced (never under a live reader)."""
        return self.lib.ns_evict(self.handle, oid.binary())

    def acquire(self, oid: ObjectID):
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        state = self.lib.ns_acquire(
            self.handle, oid.binary(), os.getpid(), ctypes.byref(off),
            ctypes.byref(size))
        return state, off.value, size.value

    def acquire_for(self, oid: ObjectID, pid: int) -> int:
        """Take a read reference on behalf of ANOTHER process (the
        restore handshake — see NativeShmStore._lease_for_locked).
        Reaped with the rest of the pid's references if it dies."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        return self.lib.ns_acquire(
            self.handle, oid.binary(), pid, ctypes.byref(off),
            ctypes.byref(size))

    def release_for(self, oid: ObjectID, pid: int) -> None:
        self.lib.ns_release(self.handle, oid.binary(), pid)

    def release(self, oid: ObjectID) -> None:
        self.lib.ns_release(self.handle, oid.binary(), os.getpid())

    def release_all(self) -> None:
        self.lib.ns_release_all(self.handle, os.getpid())

    def reap(self) -> int:
        return self.lib.ns_reap(self.handle)

    def largest_free(self) -> int:
        return self.lib.ns_largest_free(self.handle)

    def compact(self) -> int:
        """Defragment movable (sealed, reader-free) extents; returns the
        largest contiguous free run afterwards."""
        return self.lib.ns_compact(self.handle)

    def stats(self):
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        n = ctypes.c_uint32()
        self.lib.ns_stats(self.handle, ctypes.byref(used),
                          ctypes.byref(cap), ctypes.byref(n))
        return used.value, cap.value, n.value

    def list_sealed(self, max_n: Optional[int] = None):
        """[(ObjectID, size, refcnt)] of every sealed object in the
        segment — the authoritative inventory for spill/eviction (the
        index, not notifications, is the source of truth). Buffers are
        sized from the live object count: this runs under the store
        lock on every eviction sweep, so fixed 2.6MB allocations would
        tax exactly the pressure episodes it serves."""
        if max_n is None:
            _, _, n_live = self.stats()
            max_n = max(64, min(65536, int(n_live) + 64))
        ids = (ctypes.c_uint8 * (28 * max_n))()
        sizes = (ctypes.c_uint64 * max_n)()
        refs = (ctypes.c_uint32 * max_n)()
        n = self.lib.ns_list(self.handle, ids, sizes, refs, max_n)
        raw = bytes(ids)
        return [(ObjectID(raw[i * 28:(i + 1) * 28]),
                 sizes[i], refs[i]) for i in range(n)]

    def close(self, unlink: bool = False) -> None:
        try:
            self.view.release()
        except Exception:
            pass
        if self.handle:
            self.lib.ns_close(self.handle)
            self.handle = None
        if unlink:
            try:
                os.unlink(self.path)
            except FileNotFoundError:
                pass


class NativeShmStore:
    """Server side (node manager): eviction/spill authority over the
    native segment. API-compatible with ``ShmObjectStore``."""

    def __init__(self, session_name: str, capacity_bytes: int,
                 spill_dir: Optional[str] = None, lib=None):
        from ray_tpu import _native
        self.lib = lib or _native.load()
        assert self.lib is not None
        self.session_name = session_name
        self.capacity = capacity_bytes
        # Physical segment is over-provisioned (tmpfs pages materialize
        # only when touched, so unused headroom costs nothing) — a
        # create that overshoots the nominal capacity succeeds while
        # eviction/spilling works back toward the budget. This is
        # plasma's "fallback allocation" escape valve: the in-flight
        # working set (reader-leased extents of executing tasks) may
        # legitimately exceed the budget, and refusing creates then
        # deadlocks the pipeline that would have released those leases.
        self.seg = _Segment(self.lib, session_name,
                            capacity=capacity_bytes * 4)
        self.spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._sealed: "OrderedDict[ObjectID, int]" = OrderedDict()
        self._pinned: Dict[ObjectID, int] = {}
        self._spilled: Dict[ObjectID, str] = {}
        #: consecutive failed restore reads per object (disk faults):
        #: below the cap the failure is reported transient ("retry");
        #: at the cap the backing copy is declared lost so the
        #: controller can re-pull from another holder
        self._restore_strikes: Dict[ObjectID, int] = {}
        #: seeded spill-path fault injection (chaos.py); None in
        #: production — the spill/restore hot path stays untouched
        self._disk_chaos = None
        if spill_dir:
            try:
                from ray_tpu.core import chaos as _chaos
                self._disk_chaos = _chaos.maybe_disk_injector("node")
            except Exception:
                pass
        #: freshly-restored objects are exempt from spilling briefly —
        #: without the grace window, memory pressure can re-spill an
        #: object between its restore RPC reply and the requester's
        #: first read lease (restore/spill livelock)
        self._restore_grace: Dict[ObjectID, float] = {}
        # Background prefault (bounded): once tmpfs pages exist, every
        # client mapping reaches memcpy-class put bandwidth; unfaulted
        # tails are handled per-create by _madvise_populate.
        from ray_tpu.core.config import get_config
        budget = min(self.seg.total,
                     get_config().object_store_prefault_bytes)
        if budget > 0:
            t = threading.Thread(target=self._prefault, args=(budget,),
                                 name="store-prefault", daemon=True)
            t.start()

    def _prefault(self, budget: int) -> None:
        chunk = 256 << 20
        for off in range(0, budget, chunk):
            if self.seg.handle is None:
                return
            _madvise_populate(self.seg.base, off,
                              min(chunk, budget - off))

    # --- bookkeeping (same contract as ShmObjectStore) ---
    def on_sealed(self, object_id: ObjectID, size: int,
                  grace: bool = False) -> None:
        with self._lock:
            self._sealed[object_id] = size
            if grace:
                # fresh-arrival grace (transfer receives), same
                # rationale as the restore grace: an object pulled FOR
                # a waiting consumer must not be re-spilled before that
                # consumer takes its read lease (observed as a
                # re-pull/re-spill livelock when an over-budget
                # object's only healthy copy is remote and the local
                # backing copy is disk-faulted)
                self._restore_grace[object_id] = time.monotonic() + 2.0
            self._maybe_evict_locked()

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            self._pinned[object_id] = self._pinned.get(object_id, 0) + 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            n = self._pinned.get(object_id, 0) - 1
            if n <= 0:
                self._pinned.pop(object_id, None)
            else:
                self._pinned[object_id] = n

    def contains(self, object_id: ObjectID) -> bool:
        state, _, _ = self.seg.lookup(object_id)
        if state == 2:
            return True
        with self._lock:
            return object_id in self._spilled

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._delete_locked(object_id)

    def _delete_locked(self, object_id: ObjectID) -> None:
        if STORE_DEBUG:
            logger.info("DELETE %s", object_id.hex())
        self._sealed.pop(object_id, None)
        self.seg.delete(object_id)
        spath = self._spilled.pop(object_id, None)
        if spath:
            try:
                os.unlink(spath)
            except FileNotFoundError:
                pass

    def _evict_candidates_locked(self):
        """Spill/evict candidates: segment-indexed sealed objects (the
        segment is the source of truth — workers create/seal without
        notifying this authority) that are neither pinned, reader-held,
        nor inside the restore-grace window. Notified objects (_sealed,
        transfer receives) come first in their LRU order."""
        now = time.monotonic()
        for oid in [o for o, t in self._restore_grace.items()
                    if t < now]:
            del self._restore_grace[oid]
        skip = self._restore_grace
        listed = self.seg.list_sealed()
        refcnt_of = {oid: rc for oid, _sz, rc in listed}
        seen = set(self._sealed.keys())
        # reader-held extents are unspillable (seg.evict would refuse
        # AFTER the disk write): filter by refcount everywhere
        out = [oid for oid in self._sealed.keys()
               if oid not in self._pinned and oid not in skip
               and refcnt_of.get(oid, 0) == 0]
        out += [oid for oid, _sz, rc in listed
                if rc == 0 and oid not in self._pinned
                and oid not in skip and oid not in seen]
        return out

    def maybe_evict(self) -> None:
        """Background spill/eviction toward the nominal budget (called
        from the node heartbeat): keeps resident bytes near capacity so
        foreground creates almost never stall on make_room."""
        with self._lock:
            self._maybe_evict_locked()

    def make_room(self, bytes_needed: int) -> int:
        """Spill/evict LRU unpinned sealed objects until at least
        ``bytes_needed`` of segment DATA capacity is free (or nothing
        more can move). The worker-side create retries after this — the
        reference's create-request-queue semantics
        (plasma/create_request_queue.h), server-authoritative."""
        freed = 0
        with self._lock:
            # bounded per call: spilling is disk I/O under the store
            # lock, and a concurrent restore RPC waiting on this lock
            # must not starve past its caller's deadline — callers loop
            # (runtime create retry), so partial progress is fine
            moved = 0
            for oid in self._evict_candidates_locked():
                # free space measured against the DATA area (ns_stats
                # capacity), not the mapped size (which counts header/
                # slot-table overhead as if it were allocatable)
                used, cap, _ = self.seg.stats()
                if cap - used >= bytes_needed or moved >= 8:
                    break
                before = used
                if self.spill_dir:
                    self._spill_locked(oid)
                elif self.seg.evict(oid) > 0:
                    self._sealed.pop(oid, None)
                moved += 1
                after, _, _ = self.seg.stats()
                freed += max(0, before - after)
            # Fragmentation defense: enough total bytes can be free with
            # no CONTIGUOUS run large enough (pinned extents scattered
            # across the arena) — compact the movable extents before the
            # caller's create retries (observed: 17 MB creates failing
            # at 25% utilization of a 192 MB arena)
            used, cap, _ = self.seg.stats()
            if cap - used >= bytes_needed and \
                    self.seg.largest_free() < bytes_needed:
                self.seg.compact()
        return freed

    def _maybe_evict_locked(self) -> None:
        # Evict against the NOMINAL capacity; the physical segment has
        # headroom so in-flight creates don't fail while we catch up.
        used, _, _ = self.seg.stats()
        if used <= self.capacity:
            return
        moved = 0
        for oid in self._evict_candidates_locked():
            used, _, _ = self.seg.stats()
            if used <= self.capacity * 0.8 or moved >= 8:
                # bounded sweep: the heartbeat calls again next tick;
                # unbounded spilling would hold the lock through many
                # seconds of disk writes and stall restore RPCs
                break
            if self.spill_dir:
                self._spill_locked(oid)
            elif self.seg.evict(oid) > 0:
                self._sealed.pop(oid, None)
            moved += 1

    def _spill_locked(self, object_id: ObjectID) -> None:
        state, off, size = self.seg.lookup(object_id)
        if state != 2:
            return
        dst = os.path.join(self.spill_dir, object_id.hex())
        already = self._spilled.get(object_id)
        if already is None:
            # don't rewrite an existing backing copy: the object can be
            # in BOTH places when a duplicate execution (at-least-once
            # resubmit) re-created an already-spilled object's extent
            try:
                if self._disk_chaos is not None:
                    kind = self._disk_chaos.fault("spill_write")
                    if kind == "enospc":
                        raise OSError(errno.ENOSPC,
                                      "injected spill ENOSPC")
                    if kind is not None:
                        raise OSError(errno.EIO, "injected spill EIO")
                with open(dst, "wb") as f:
                    f.write(self.seg.view[off:off + size])
            except OSError as e:
                # the disk refused the spill (EIO/ENOSPC, injected or
                # real): the extent is still the only copy — keep it
                # resident, drop the partial file, and let a later
                # sweep retry. Pressure degrades to no-progress here
                # instead of data loss.
                try:
                    os.unlink(dst)
                except OSError:
                    pass
                logger.warning("spill of %s failed (%s); keeping the "
                               "object resident", object_id.hex()[:12], e)
                return
        if self.seg.evict(object_id) == 0:
            # A live reader holds the extent; leave it resident. Only
            # remove the file WE just wrote — unlinking a pre-existing
            # backing copy here would strand _spilled pointing at
            # nothing (observed as ObjectLost under spill pressure).
            if already is None:
                try:
                    os.unlink(dst)
                except FileNotFoundError:
                    pass
            return
        self._sealed.pop(object_id, None)
        self._spilled[object_id] = dst
        if STORE_DEBUG:
            logger.info("SPILL %s", object_id.hex())

    def _lease_for_locked(self, object_id: ObjectID,
                          for_pid: Optional[int]) -> None:
        """Take a reader lease ON BEHALF OF the requesting pid before
        the restore RPC reply leaves this process. Closes the
        restore-vs-respill race outright: the extent cannot be spilled
        or evicted again until the requester maps it and releases (the
        grace window only narrowed the race; under sustained spill
        thrash the reply could arrive after the object was re-spilled
        and the get would eventually give up). Leases of crashed
        requesters are reclaimed by reap_dead_readers. Reference:
        ``src/ray/raylet/local_object_manager.h:41`` — spilled objects
        are pinned through the restore handshake."""
        if for_pid:
            self.seg.acquire_for(object_id, int(for_pid))

    def _local_copy_lost_locked(self, object_id: ObjectID,
                                spath: str) -> str:
        """The backing copy is unusable (persistent EIO / truncation):
        forget it so location lookups stop routing here — the caller
        reports the stale holder and the controller re-pulls from
        another holder or reconstructs via lineage. Only after THOSE
        fail does anything surface ObjectLostError."""
        self._restore_strikes.pop(object_id, None)
        self._spilled.pop(object_id, None)
        try:
            os.unlink(spath)
        except OSError:
            pass
        return "lost"

    def maybe_restore(self, object_id: ObjectID,
                      for_pid: Optional[int] = None) -> bool:
        """True = resident (restored or already there); "retry" =
        transient pressure/fault, ask again; "lost" = the local backing
        copy is gone for good (re-pull from another holder); False =
        this node never had it."""
        with self._lock:
            spath = self._spilled.get(object_id)
            if spath is None:
                state, _, _ = self.seg.lookup(object_id)
                if state != 2 and STORE_DEBUG:
                    logger.warning(
                        "RESTOREMISS %s state=%s nspilled=%d",
                        object_id.hex(), state, len(self._spilled))
                if state == 2:
                    self._lease_for_locked(object_id, for_pid)
                    return True
                return False
            if self.seg.lookup(object_id)[0] == 2:
                # resident AND spilled (duplicate-execution re-create):
                # the extent is current; keep the disk copy as backup
                self._lease_for_locked(object_id, for_pid)
                return True
            try:
                size = os.stat(spath).st_size
            except FileNotFoundError:
                # backing file vanished (historical unlink bug, manual
                # cleanup): surface not-restorable instead of raising
                self._spilled.pop(object_id, None)
                return False
            off = self.seg.alloc(object_id, size)
            if off == _FULL:
                # fragmentation first: compaction is cheaper than
                # spilling and may already open a large-enough run
                self.seg.compact()
                off = self.seg.alloc(object_id, size)
            if off == _FULL:
                # Make room by SPILLING other unreferenced residents
                # (never plain eviction here — an unspilled resident's
                # only copy may live in this segment), then retry.
                for other in self._evict_candidates_locked():
                    if other == object_id:
                        continue
                    if self.spill_dir:
                        self._spill_locked(other)
                    elif self.seg.evict(other) > 0:
                        self._sealed.pop(other, None)
                    off = self.seg.alloc(object_id, size)
                    if off != _FULL:
                        break
            if off == _EXISTS:
                # duplicate execution re-created the extent while we
                # looked at the spill index: it is resident — and the
                # handshake lease must STILL be taken (the node reports
                # leased=True on every ok reply; an unbalanced release
                # would zero the requester's own reader ref and let
                # compaction move the extent under its live view)
                self._lease_for_locked(object_id, for_pid)
                return True
            if off == _FULL:
                # the backing copy EXISTS but the segment can't admit it
                # right now (remaining extents reader-held or in their
                # restore grace): transient — callers must retry, not
                # declare the object lost
                return "retry"
            try:
                with open(spath, "rb") as f:
                    n_read = f.readinto(self.seg.view[off:off + size])
                if self._disk_chaos is not None:
                    kind = self._disk_chaos.fault("restore_read")
                    if kind == "truncate":
                        n_read = size // 2
                    elif kind is not None:
                        raise OSError(errno.EIO, "injected restore EIO")
            except OSError as e:
                # transient I/O failure: free the half-written extent so
                # a retry can re-alloc, and back off through the caller.
                # A few consecutive strikes declare the copy unusable.
                self.seg.delete(object_id)
                strikes = self._restore_strikes.get(object_id, 0) + 1
                self._restore_strikes[object_id] = strikes
                logger.warning("restore of %s failed (%s), strike %d",
                               object_id.hex()[:12], e, strikes)
                if strikes < 3:
                    return "retry"
                return self._local_copy_lost_locked(object_id, spath)
            if n_read < size:
                # truncated backing file (torn write, disk corruption):
                # retrying cannot heal it — drop the copy immediately
                self.seg.delete(object_id)
                logger.warning(
                    "restore of %s: truncated backing file (%d/%d "
                    "bytes)", object_id.hex()[:12], n_read, size)
                return self._local_copy_lost_locked(object_id, spath)
            self.seg.seal(object_id)
            os.unlink(spath)
            self._restore_strikes.pop(object_id, None)
            self._spilled.pop(object_id, None)
            self._sealed[object_id] = size
            self._restore_grace[object_id] = time.monotonic() + 2.0
            self._lease_for_locked(object_id, for_pid)
            return True

    def reap_dead_readers(self) -> int:
        """Release references held by dead PIDs (crash cleanup;
        plasma's disconnected-client path). Called from the node
        manager's heartbeat."""
        return self.seg.reap()

    def contents(self):
        """[(object_id_binary, size)] of every sealed (incl. spilled)
        object — the node re-announces these to a restarted controller."""
        with self._lock:
            out = [(oid.binary(), sz) for oid, sz in self._sealed.items()]
            out.extend((oid.binary(), 0) for oid in self._spilled)
            return out

    def stats(self) -> dict:
        used, _, n = self.seg.stats()
        with self._lock:
            return {
                "used_bytes": used,
                "capacity_bytes": self.capacity,
                "num_objects": n,
                "num_spilled": len(self._spilled),
                "num_pinned": len(self._pinned),
                "native": True,
            }

    def destroy(self) -> None:
        with self._lock:
            for spath in self._spilled.values():
                try:
                    os.unlink(spath)
                except FileNotFoundError:
                    pass
            self._spilled.clear()
        self.seg.close(unlink=True)


class NativeShmClient:
    """Worker/driver side: zero-copy create/seal/get on the segment.
    API-compatible with ``ShmClient``."""

    def __init__(self, session_name: str, lib=None):
        from ray_tpu import _native
        self.lib = lib or _native.load()
        assert self.lib is not None
        self.session_name = session_name
        self._seg: Optional[_Segment] = None
        self._acquired: Dict[ObjectID, int] = {}
        self._lock = threading.Lock()
        #: extents this client already madvise-populated. Recycled
        #: extents come back at the same (off, size) with their pages
        #: resident AND present in our page table, so the syscall
        #: (~0.6ms per 64MB: a PTE walk over 16k pages) is pure waste
        #: on every put after the first. Bounded LRU-ish set.
        self._populated: "OrderedDict[tuple, None]" = OrderedDict()

    def _segment(self, timeout: float = 10.0) -> _Segment:
        with self._lock:
            if self._seg is None:
                deadline = time.monotonic() + timeout
                while True:
                    try:
                        self._seg = _Segment(self.lib, self.session_name)
                        break
                    except OSError:
                        if time.monotonic() >= deadline:
                            raise
                        time.sleep(0.01)
            return self._seg

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        seg = self._segment()
        off = seg.alloc(object_id, size)
        if off == _EXISTS:
            raise FileExistsError(object_id.hex())
        if off == _FULL:
            raise ObjectStoreFullError(
                f"native store full creating {object_id.hex()} "
                f"({size} bytes)")
        size = max(size, 1)
        if size >= 1 << 20:
            # prefault large extents so the serializer's memcpy doesn't
            # eat a page trap per 4 KiB (plasma gets this for free from
            # dlmalloc recycling; our recycled extents do too — this
            # covers first-touch). Skipped when WE already populated
            # this exact extent (hot put loops recycle one extent).
            key = (off, size)
            if key not in self._populated:
                _madvise_populate(seg.base, off, size)
                self._populated[key] = None
                while len(self._populated) > 1024:
                    self._populated.popitem(last=False)
        return seg.view[off:off + size]

    def seal(self, object_id: ObjectID) -> int:
        size = self._segment().seal(object_id)
        return 0 if size == _FULL else size

    def put_bytes(self, object_id: ObjectID, data) -> int:
        view = self.create(object_id, len(data))
        view[: len(data)] = data
        return self.seal(object_id)

    def get_view(self, object_id: ObjectID,
                 timeout: float = 0.0) -> Optional[memoryview]:
        """Zero-copy view; takes a read reference so the extent cannot
        be reused under us. Balanced by release()/close(); references
        of crashed processes are reaped by the node manager."""
        seg = self._segment()
        deadline = time.monotonic() + timeout
        while True:
            state, off, size = seg.acquire(object_id)
            if state == 2:
                with self._lock:
                    self._acquired[object_id] = \
                        self._acquired.get(object_id, 0) + 1
                return seg.view[off:off + size]
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.001)

    def contains(self, object_id: ObjectID) -> bool:
        state, _, _ = self._segment().lookup(object_id)
        return state == 2

    def evict(self, object_id: ObjectID) -> int:
        """Free the extent now if (and only if) no reader holds it.
        Returns freed bytes, 0 if skipped. Owner-side eager recycling:
        freed extents go back on the allocator freelist with their tmpfs
        pages still resident, so the next same-sized create skips the
        page-population cost entirely."""
        return self._segment().evict(object_id)

    def release(self, object_id: ObjectID) -> None:
        with self._lock:
            n = self._acquired.get(object_id, 0)
            if n <= 0 or self._seg is None:
                return
            if n == 1:
                self._acquired.pop(object_id, None)
            else:
                self._acquired[object_id] = n - 1
        self._seg.release(object_id)

    def close(self) -> None:
        with self._lock:
            if self._seg is not None:
                self._seg.release_all()
                self._acquired.clear()
                self._seg.close()
                self._seg = None
