"""Controller state persistence: snapshot + write-ahead log.

Reference: the GCS persists its tables through a store client
(``src/ray/gcs/store_client/redis_store_client.h`` — Redis in production,
in-memory otherwise) so ``gcs_server`` restart recovers actors/KV/jobs,
and raylets re-register on reconnect (``node_manager.cc:1114``). Here the
durable store is a length-prefixed pickle WAL in the session directory
(one host owns the controller; a TPU-pod control plane does not need a
Redis dependency), compacted into a snapshot when the log grows.

What is durable: the KV store, exported functions, the named-actor
directory (spec + name), and the job counter. Everything else — node
membership, worker pools, object locations, in-flight tasks — is owned
by processes that outlive the controller and is reconstructed through
the RECONNECT re-announcement protocol, mirroring the reference's
"GCS is recoverable state + resubscribe" design.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ray_tpu.core import protocol as P

_LEN = struct.Struct("<I")


class ControllerStore:
    """Append-only op log with snapshot compaction."""

    def __init__(self, session_dir: str, compact_every: int = 10_000):
        self.dir = os.path.join(session_dir, "controller_state")
        os.makedirs(self.dir, exist_ok=True)
        self.snap_path = os.path.join(self.dir, "snapshot.bin")
        self.wal_path = os.path.join(self.dir, "wal.bin")
        self.compact_every = compact_every
        self._lock = threading.Lock()
        self._ops_since_snap = 0
        self._wal = open(self.wal_path, "ab")

    # ------------------------------------------------------------- write
    def append(self, op: Tuple) -> None:
        blob = P.dumps(op)
        with self._lock:
            self._wal.write(_LEN.pack(len(blob)) + blob)
            self._wal.flush()
            # the ack a client sees after this append must survive power
            # loss, same durability the snapshot path promises
            os.fsync(self._wal.fileno())
            self._ops_since_snap += 1

    def maybe_compact(self, state_fn: Callable[[], dict]) -> None:
        """Replace snapshot+log with a fresh snapshot when the log is
        long. ``state_fn`` must return the full durable state."""
        with self._lock:
            if self._ops_since_snap < self.compact_every:
                return
        self.snapshot(state_fn())

    def snapshot(self, state: dict) -> None:
        tmp = self.snap_path + ".tmp"
        blob = P.dumps(state)
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            self._wal.close()
            self._wal = open(self.wal_path, "wb")  # truncate
            self._ops_since_snap = 0

    # -------------------------------------------------------------- read
    def load(self) -> Tuple[Optional[dict], List[Tuple]]:
        """(snapshot state or None, ops appended since the snapshot).
        A torn trailing WAL record (crash mid-append) is dropped."""
        snap = None
        if os.path.exists(self.snap_path):
            try:
                with open(self.snap_path, "rb") as f:
                    snap = P.loads(f.read())
            except Exception:
                snap = None
        ops: List[Tuple] = []
        try:
            with open(self.wal_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raw = b""
        off = 0
        while off + _LEN.size <= len(raw):
            (n,) = _LEN.unpack_from(raw, off)
            if off + _LEN.size + n > len(raw):
                break  # torn tail
            try:
                ops.append(P.loads(raw[off + _LEN.size:off + _LEN.size + n]))
            except Exception:
                break
            off += _LEN.size + n
        return snap, ops

    def close(self) -> None:
        with self._lock:
            try:
                self._wal.close()
            except Exception:
                pass
