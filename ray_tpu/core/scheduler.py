"""Cluster resource scheduler: node selection policies + bundle placement.

Equivalent of the reference's two-level scheduler
(``src/ray/raylet/scheduling/cluster_resource_scheduler.h:96`` +
``scheduling/policy/``). Design difference: the reference keeps local truth
in each raylet with a gossiped view (ray_syncer); here the controller is the
single resource-accounting authority, so scheduling is consistent by
construction and the "spillback" path disappears. Policies implemented:

- **hybrid** (default, ``hybrid_scheduling_policy.h:50``): prefer packing
  onto non-idle feasible nodes whose critical-resource utilization is below
  ``scheduler_spread_threshold``; above it, prefer the least utilized
  (spread); pick among the top-k for tie-breaking.
- **spread** (round-robin over feasible nodes),
- **node-affinity** (hard/soft, ``scheduling_strategies.py:41``),
- **node-label** (hard/soft label matching),
- **placement-group bundles** (``bundle_scheduling_policy.h``): PACK /
  SPREAD / STRICT_PACK / STRICT_SPREAD, plus the TPU-native gang pair
  SLICE_PACK / SLICE_SPREAD.

TPU-specific: pod-slice gang placement. Every host VM of a slice
registers with the slice's id in its ``ray-tpu-slice-id`` label
(stamped by the cluster launcher / slice providers; reference:
``python/ray/_private/accelerators/tpu.py:379-382`` pins gangs via a
``TPU-{pod_type}-head`` resource — here the label IS the gang key).
``SLICE_SPREAD`` bundles land on DISTINCT ICI-connected hosts of ONE
slice; ``SLICE_PACK`` packs all bundles onto one slice's hosts with
co-residency allowed. Both are all-or-nothing: no slice admits the
whole gang → the group stays pending (never a partial reservation).
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import NodeID, PlacementGroupID
from ray_tpu.core.task_spec import Bundle, PlacementGroupSpec, SchedulingStrategy

EPS = 1e-9

#: node label carrying the provider slice id: every host VM of a TPU
#: slice registers with it, so SLICE_* placement groups can gang over
#: hosts that share one ICI domain. The GCE provider's per-slice node
#: label (``ray-tpu-node-id``, one provider node == one slice) is
#: accepted as a fallback spelling.
SLICE_LABEL = "ray-tpu-slice-id"


def node_slice_id(labels: Dict[str, str]) -> Optional[str]:
    """The slice a node belongs to, or None for loose nodes."""
    return labels.get(SLICE_LABEL) or labels.get("ray-tpu-node-id")


class NodeResources:
    __slots__ = ("node_id", "total", "available", "labels", "alive", "idle",
                 "draining")

    def __init__(self, node_id: NodeID, total: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None):
        self.node_id = node_id
        self.total = dict(total)
        self.available = dict(total)
        self.labels = labels or {}
        self.alive = True
        self.idle = True
        #: autoscaler is about to terminate this node: place nothing new
        #: (reference: DrainNode RPC before termination, node_manager.cc)
        self.draining = False

    def feasible(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) + EPS >= v for k, v in demand.items())

    def fits(self, demand: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0.0) + EPS >= v for k, v in demand.items())

    def acquire(self, demand: Dict[str, float]) -> bool:
        if not self.fits(demand):
            return False
        for k, v in demand.items():
            self.available[k] = self.available.get(k, 0.0) - v
        self.idle = False
        return True

    def release(self, demand: Dict[str, float]) -> None:
        for k, v in demand.items():
            self.available[k] = min(self.total.get(k, 0.0),
                                    self.available.get(k, 0.0) + v)

    def critical_utilization(self, demand: Dict[str, float]) -> float:
        """Max over demanded resources of (used / total) — the reference's
        packing key (hybrid_scheduling_policy.cc)."""
        util = 0.0
        for k in (demand or self.total):
            t = self.total.get(k, 0.0)
            if t <= 0:
                continue
            used = t - self.available.get(k, 0.0)
            util = max(util, used / t)
        return util


class ClusterResourceScheduler:
    def __init__(self):
        self._lock = threading.RLock()
        self.nodes: Dict[NodeID, NodeResources] = {}
        self._spread_rr = 0
        self._rng = random.Random(0)
        # pg_id -> list of (node_id, resources) actually reserved
        self._pg_reservations: Dict[PlacementGroupID, List[Tuple[NodeID, Dict[str, float]]]] = {}

    # ---- membership ----
    def add_node(self, node: NodeResources) -> None:
        with self._lock:
            self.nodes[node.node_id] = node

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            self.nodes.pop(node_id, None)

    def get_node(self, node_id: NodeID) -> Optional[NodeResources]:
        with self._lock:
            return self.nodes.get(node_id)

    # ---- selection ----
    def pick_node(self, demand: Dict[str, float],
                  strategy: SchedulingStrategy) -> Optional[NodeID]:
        """Returns the chosen node and acquires the resources, or None if
        nothing fits right now (caller queues the task)."""
        with self._lock:
            if strategy.kind == "PLACEMENT_GROUP":
                # bundle resources are pre-reserved; just pick the node
                nodes = self._pg_reservations.get(strategy.placement_group_id, [])
                if not nodes:
                    return None
                idx = strategy.placement_group_bundle_index
                if 0 <= idx < len(nodes):
                    return nodes[idx][0]
                return nodes[0][0]
            if strategy.kind == "NODE_AFFINITY":
                return self._pick_affinity(demand, strategy)
            if strategy.kind == "NODE_LABEL":
                return self._pick_label(demand, strategy)
            if strategy.kind == "SPREAD":
                return self._pick_spread(demand)
            return self._pick_hybrid(demand)

    def _alive_nodes(self) -> List[NodeResources]:
        return [n for n in self.nodes.values() if n.alive and not n.draining]

    def set_draining(self, node_id: NodeID, draining: bool) -> None:
        with self._lock:
            n = self.nodes.get(node_id)
            if n is not None:
                n.draining = draining

    def _acquire(self, node: NodeResources, demand: Dict[str, float]) -> Optional[NodeID]:
        return node.node_id if node.acquire(demand) else None

    def _pick_hybrid(self, demand: Dict[str, float]) -> Optional[NodeID]:
        cfg = get_config()
        candidates = [n for n in self._alive_nodes() if n.fits(demand)]
        if not candidates:
            return None
        below = [n for n in candidates
                 if n.critical_utilization(demand) < cfg.scheduler_spread_threshold]
        if below:
            # pack: highest utilization first (most packed feasible node)
            below.sort(key=lambda n: (-n.critical_utilization(demand), n.node_id))
            pool = below
        else:
            # spread: least utilized first
            candidates.sort(key=lambda n: (n.critical_utilization(demand), n.node_id))
            pool = candidates
        k = max(cfg.scheduler_top_k_absolute,
                int(len(pool) * cfg.scheduler_top_k_fraction))
        choice = self._rng.choice(pool[:k])
        return self._acquire(choice, demand)

    def _pick_spread(self, demand: Dict[str, float]) -> Optional[NodeID]:
        nodes = sorted(self._alive_nodes(), key=lambda n: n.node_id)
        if not nodes:
            return None
        for i in range(len(nodes)):
            n = nodes[(self._spread_rr + i) % len(nodes)]
            if n.fits(demand):
                self._spread_rr = (self._spread_rr + i + 1) % len(nodes)
                return self._acquire(n, demand)
        return None

    def _pick_affinity(self, demand, strategy) -> Optional[NodeID]:
        n = self.nodes.get(strategy.node_id)
        if n is not None and n.alive and n.fits(demand):
            return self._acquire(n, demand)
        if strategy.soft:
            return self._pick_hybrid(demand)
        return None

    def _pick_label(self, demand, strategy) -> Optional[NodeID]:
        def matches(n, labels):
            return all(n.labels.get(k) in v for k, v in labels.items())
        hard = [n for n in self._alive_nodes()
                if n.fits(demand) and matches(n, strategy.hard_labels)]
        if not hard:
            return None
        soft = [n for n in hard if matches(n, strategy.soft_labels)]
        pool = soft or hard
        pool.sort(key=lambda n: (n.critical_utilization(demand), n.node_id))
        return self._acquire(pool[0], demand)

    def release(self, node_id: NodeID, demand: Dict[str, float]) -> None:
        with self._lock:
            n = self.nodes.get(node_id)
            if n is not None:
                n.release(demand)

    def try_acquire(self, node_id: NodeID,
                    demand: Dict[str, float]) -> bool:
        """Acquire resources on a SPECIFIC node (worker-lease grants)."""
        with self._lock:
            n = self.nodes.get(node_id)
            return n is not None and n.alive and n.acquire(demand)

    def force_acquire(self, node_id: NodeID, demand: Dict[str, float]) -> None:
        """Unconditional acquisition for a resuming blocked worker: may
        drive availability transiently negative (visible backpressure that
        self-corrects as other tasks release)."""
        with self._lock:
            n = self.nodes.get(node_id)
            if n is not None:
                for k, v in demand.items():
                    n.available[k] = n.available.get(k, 0.0) - v

    # ---- placement groups (reference: bundle_scheduling_policy.h +
    # gcs_placement_group_scheduler.h 2PC; single-authority here) ----
    def reserve_placement_group(self, spec: PlacementGroupSpec) -> bool:
        """Atomically reserve all bundles, or nothing."""
        with self._lock:
            plan = self._plan_bundles(spec)
            if plan is None:
                return False
            reserved = []
            ok = True
            for bundle, node_id in plan:
                node = self.nodes[node_id]
                if node.acquire(bundle.resources):
                    reserved.append((node_id, dict(bundle.resources)))
                else:
                    ok = False
                    break
            if not ok:
                for node_id, res in reserved:
                    self.nodes[node_id].release(res)
                return False
            for (bundle, node_id) in plan:
                bundle.node_id = node_id
            self._pg_reservations[spec.pg_id] = reserved
            return True

    def _plan_bundles(self, spec: PlacementGroupSpec
                      ) -> Optional[List[Tuple[Bundle, NodeID]]]:
        nodes = self._alive_nodes()
        if spec.strategy in ("SLICE_PACK", "SLICE_SPREAD"):
            return self._plan_slice_bundles(spec, nodes)
        if spec.strategy in ("STRICT_PACK",):
            # all bundles on one node; TPU slices: prefer nodes sharing a
            # slice_id label whose head carries the gang resource.
            merged: Dict[str, float] = {}
            for b in spec.bundles:
                for k, v in b.resources.items():
                    merged[k] = merged.get(k, 0.0) + v
            for n in sorted(nodes, key=lambda n: -n.critical_utilization(merged)):
                if n.fits(merged):
                    return [(b, n.node_id) for b in spec.bundles]
            return None
        if spec.strategy == "STRICT_SPREAD":
            plan = []
            used = set()
            for b in spec.bundles:
                placed = False
                for n in sorted(nodes, key=lambda n: n.critical_utilization(b.resources)):
                    if n.node_id in used:
                        continue
                    if n.fits(b.resources):
                        plan.append((b, n.node_id))
                        used.add(n.node_id)
                        placed = True
                        break
                if not placed:
                    return None
            return plan
        # PACK (best effort single node, fall back) / SPREAD (best effort)
        plan = []
        # simulate availability so multiple bundles on one node are counted
        sim: Dict[NodeID, Dict[str, float]] = {
            n.node_id: dict(n.available) for n in nodes}

        def sim_fits(nid, res):
            av = sim[nid]
            return all(av.get(k, 0.0) + EPS >= v for k, v in res.items())

        def sim_take(nid, res):
            av = sim[nid]
            for k, v in res.items():
                av[k] = av.get(k, 0.0) - v

        prefer_pack = spec.strategy == "PACK"
        last: Optional[NodeID] = None
        for b in spec.bundles:
            order = sorted(
                nodes,
                key=lambda n: (
                    0 if (prefer_pack and n.node_id == last) else 1,
                    -n.critical_utilization(b.resources) if prefer_pack
                    else n.critical_utilization(b.resources),
                ),
            )
            placed = False
            for n in order:
                if spec.strategy == "SPREAD" and n.node_id == last and len(nodes) > 1:
                    continue
                if sim_fits(n.node_id, b.resources):
                    sim_take(n.node_id, b.resources)
                    plan.append((b, n.node_id))
                    last = n.node_id
                    placed = True
                    break
            if not placed:
                return None
        return plan

    def _plan_slice_bundles(self, spec: PlacementGroupSpec,
                            nodes: List[NodeResources]
                            ) -> Optional[List[Tuple[Bundle, NodeID]]]:
        """Gang-plan every bundle onto the hosts of ONE slice,
        all-or-nothing. SLICE_SPREAD: one bundle per DISTINCT host (a
        gang with more bundles than a slice has hosts can never use
        that slice). SLICE_PACK: first-fit over the slice's hosts,
        co-residency allowed. Slices are tried in deterministic id
        order so repeated planning under identical state picks the
        same slice."""
        groups: Dict[str, List[NodeResources]] = {}
        for n in nodes:
            sid = node_slice_id(n.labels)
            if sid:
                groups.setdefault(sid, []).append(n)
        for sid in sorted(groups):
            hosts = sorted(groups[sid], key=lambda n: n.node_id)
            if spec.strategy == "SLICE_SPREAD" and \
                    len(spec.bundles) > len(hosts):
                continue
            sim: Dict[NodeID, Dict[str, float]] = {
                n.node_id: dict(n.available) for n in hosts}
            plan: List[Tuple[Bundle, NodeID]] = []
            used: set = set()
            ok = True
            for b in spec.bundles:
                placed = False
                for n in hosts:
                    if spec.strategy == "SLICE_SPREAD" and \
                            n.node_id in used:
                        continue
                    av = sim[n.node_id]
                    if all(av.get(k, 0.0) + EPS >= v
                           for k, v in b.resources.items()):
                        for k, v in b.resources.items():
                            av[k] = av.get(k, 0.0) - v
                        plan.append((b, n.node_id))
                        used.add(n.node_id)
                        placed = True
                        break
                if not placed:
                    ok = False
                    break
            if ok:
                return plan
        return None

    def release_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            for node_id, res in self._pg_reservations.pop(pg_id, []):
                n = self.nodes.get(node_id)
                if n is not None:
                    n.release(res)

    def pg_nodes(self, pg_id: PlacementGroupID) -> List[NodeID]:
        with self._lock:
            return [nid for nid, _ in self._pg_reservations.get(pg_id, [])]

    def bundle_labels(self, spec: PlacementGroupSpec
                      ) -> List[Dict[str, str]]:
        """Per-bundle node labels of a placed gang — the gang → mesh
        hand-off: ``ray-tpu-slice-id`` on every bundle tells the driver
        (``parallel.plan``) WHICH slice hosts the gang, so stage meshes
        and bench records can name their ICI domain."""
        with self._lock:
            out: List[Dict[str, str]] = []
            for bd in spec.bundles:
                n = self.nodes.get(bd.node_id) \
                    if bd.node_id is not None else None
                out.append(dict(n.labels) if n is not None else {})
            return out

    # ---- views ----
    def cluster_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for n in self._alive_nodes():
                for k, v in n.total.items():
                    out[k] = out.get(k, 0.0) + v
            return out

    def available_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for n in self._alive_nodes():
                for k, v in n.available.items():
                    out[k] = out.get(k, 0.0) + v
            return out
