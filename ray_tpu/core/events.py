"""Task-event flight recorder: causal traces for every control hop.

Reference: the GcsTaskManager task-event pipeline
(``src/ray/gcs/gcs_server/gcs_task_manager.cc`` fed by each worker's
``task_event_buffer.cc``) — every process appends structured task
lifecycle events into a bounded local buffer that is periodically
flushed to the head, where ``ray list tasks`` / the dashboard timeline
read them. Here the same layering, extended with **causal trace ids**:

- every control message that moves a task between processes
  (DSP/ACL/ASG/DON/CAC/RES/SIT/SEF/SCR) carries a propagated
  ``(trace id, parent span)`` pair (``TaskSpec.trace`` on spec-carrying
  messages, a ``"trace"`` payload key on the rest), so one logical task
  graph shares one trace id across every process it touches;
- each process owns a :class:`FlightRecorder` — a lock-cheap bounded
  ring (drop-oldest on overflow, counted in the
  ``runtime_events_dropped_total`` metric) flushed to the controller as
  ``TASK_EVENTS`` messages riding the reliable layer (exactly-once-
  effect, like the lifecycle messages the events describe, and
  fire-and-forget for the producer: a flush never blocks task
  progress);
- the controller aggregates the merged stream, queryable via
  ``ray_tpu.util.state.list_task_events`` / ``summarize_task_latency``,
  the dashboard (``/api/v0/events``, ``/timeline``), and the
  ``tools/timeline.py`` Perfetto exporter (:func:`build_chrome_trace`).

Event taxonomy (the ``ev`` field):

=================  =====================================================
``SUBMITTED``      owner submitted the task (driver or parent task)
``LEASED``         controller opened/assigned a worker lease for it
``DISPATCHED``     dispatch message sent toward the executing worker
``RUNNING``        worker began executing the task body
``YIELDED``        streaming generator stored+reported item ``index``
                   (a replayed prefix shows the same index from a new
                   pid — that IS the lineage replay, visually)
``FINISHED``       task body returned; ``FAILED`` carries ``error``
``RETRANSMIT``     reliable layer re-sent an unacked message (``type``,
                   ``attempt``)
``DUP_DROPPED``    receiver deduped a retransmit duplicate
``ACK_RTT``        an ack landed for a message that needed retransmits
                   (``rtt_s`` = send-to-ack, attempts included)
``CREDIT_STALL``   streaming producer blocked on the backpressure
                   window for ``seconds``
``DELIVERY_FAILED``reliable layer gave up on a message (typed error)
``STAGE_TICK``     MPMD pipeline stage interval: ``phase`` forward/
                   backward/opt/idle with ``stage``/``mb``/``dur_s``
                   and ``vs`` (virtual-stage chunk index) — rendered
                   as duration slices, so the Perfetto timeline IS
                   the pipeline-bubble visualization with per-chunk
                   forward/backward/optimizer occupancy per track
``SLICE_UP``       a TPU slice fully joined: every host VM registered
                   (``slice``/``type``/``hosts``)
``SLICE_DRAIN``    slice began draining (maintenance notice, idle
                   scale-down, or host death — ``reason``); no new
                   leases land on its hosts from this instant
``SLICE_DOWN``     slice released back to the provider; carries
                   ``dur_s`` = notice-to-release drain time, so the
                   drain window renders as a duration slice on
                   ``/timeline`` (the preemption postmortem)
``ELASTIC_NOTICE`` elastic trainer consumed a drain notice
                   (``slice``/``reason``) — recovery begins here
``ELASTIC_SNAPSHOT`` in-memory state snapshot for recovery completed;
                   ``dur_s`` = gather wall, ``live`` whether the state
                   was streamed from the running program (0 steps
                   lost) or fell back to the last periodic snapshot
``ELASTIC_RELOWER`` the plan was re-lowered onto the surviving
                   capacity (``from_plan``/``to_plan``, ``dur_s`` =
                   teardown + rebuild + reload wall)
``ELASTIC_RESUME`` training resumed; ``dur_s`` = the full
                   notice/failure-to-resume recovery window (rendered
                   as a duration slice — the recovery postmortem) and
                   ``steps_lost`` = re-executed steps
``ARBITER_PREEMPT`` the slice arbiter drained a training slice for the
                   serve fleet (``slice``/``reason``; ``dur_s`` = how
                   long serve pressure was sustained before the
                   arbiter acted — renders as the pressure window)
``ARBITER_RETURN`` serve pressure ebbed past hysteresis and the
                   arbiter returned capacity to training
                   (``reason``; ``dur_s`` = the whole borrow window,
                   preempt-to-return — the colocation postmortem)
``ARBITER_REJECT`` SLO-aware admission shed a request before it could
                   wedge a replica queue (``tenant``/``priority``/
                   ``reason``)
``RLHF_SYNC``      an in-flight weight refresh landed in a serving
                   engine between decode steps (``version``/
                   ``swap_s``/``active_slots`` — the MindSpeed-RL
                   no-drain swap; ``active_slots > 0`` proves decode
                   kept running through the refresh)
``RLHF_ROLLOUT``   a rollout round closed (``round``/``trajectories``/
                   ``tokens``/``policy_versions`` — which policies
                   generated this round's trajectories, the staleness
                   record PPO importance weights are computed against)
``KV_SHIP``        disagg prefill replica shipped a request's finished
                   KV blocks toward a decode replica (``blocks``/
                   ``bytes``/``wire`` — the hand-off's wire cost)
``KV_ADOPT``       decode replica adopted shipped KV blocks into its
                   pool + radix trie (``blocks``/``reused``/``dur_s``
                   — scatter + trie-insert wall before the first tick)
``PREFIX_MIGRATE`` warm radix-trie blocks moved off a draining replica
                   onto a survivor (``blocks``/``chains``/``dir``
                   export|import — the downscale warm-cache rescue)
=================  =====================================================
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# ---- event names -----------------------------------------------------
SUBMITTED = "SUBMITTED"
LEASED = "LEASED"
DISPATCHED = "DISPATCHED"
RUNNING = "RUNNING"
YIELDED = "YIELDED"
FINISHED = "FINISHED"
FAILED = "FAILED"
RETRANSMIT = "RETRANSMIT"
DUP_DROPPED = "DUP_DROPPED"
ACK_RTT = "ACK_RTT"
CREDIT_STALL = "CREDIT_STALL"
DELIVERY_FAILED = "DELIVERY_FAILED"
STAGE_TICK = "STAGE_TICK"
SLICE_UP = "SLICE_UP"
SLICE_DRAIN = "SLICE_DRAIN"
SLICE_DOWN = "SLICE_DOWN"
ELASTIC_NOTICE = "ELASTIC_NOTICE"
ELASTIC_SNAPSHOT = "ELASTIC_SNAPSHOT"
ELASTIC_RELOWER = "ELASTIC_RELOWER"
ELASTIC_RESUME = "ELASTIC_RESUME"
ARBITER_PREEMPT = "ARBITER_PREEMPT"
ARBITER_RETURN = "ARBITER_RETURN"
ARBITER_REJECT = "ARBITER_REJECT"
RLHF_SYNC = "RLHF_SYNC"
RLHF_ROLLOUT = "RLHF_ROLLOUT"
KV_SHIP = "KV_SHIP"
KV_ADOPT = "KV_ADOPT"
PREFIX_MIGRATE = "PREFIX_MIGRATE"

#: lifecycle events a task timeline is built from (exporter slice pairs)
LIFECYCLE = (SUBMITTED, LEASED, DISPATCHED, RUNNING, YIELDED,
             FINISHED, FAILED)

# ---- trace context ---------------------------------------------------
# A trace context is ``(trace_id, span_id)``: hex strings, propagated
# on control messages as ``(trace_id, parent_span)`` (the receiving
# task's own span id is derived from its task id, so it never ships).

_tls = threading.local()


def current() -> Optional[Tuple[str, str]]:
    """This thread's active ``(trace_id, span_id)``, or None."""
    return getattr(_tls, "ctx", None)


def set_context(trace_id: Optional[str], span_id: Optional[str]):
    """Install a trace context on this thread; returns the previous
    context (pass it to :func:`restore`)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = (trace_id, span_id) if trace_id else None
    return prev


def restore(prev) -> None:
    _tls.ctx = prev


def new_span_id() -> str:
    return os.urandom(8).hex()


def child_trace(task_id_hex: str) -> Tuple[str, Optional[str]]:
    """The ``(trace_id, parent_span)`` pair to stamp on a submission:
    inherits the submitting thread's trace (a task executing under a
    propagated context, or a ``tracing.span``), else the new task roots
    its own trace."""
    cur = current()
    if cur is not None:
        return (cur[0], cur[1])
    return (task_id_hex[:32], None)


def task_trace(task_id_hex: str, trace: Optional[tuple]
               ) -> Tuple[str, str, Optional[str]]:
    """Resolve a task's full ``(trace_id, span_id, parent_span)`` from
    its propagated ``TaskSpec.trace`` (``(trace_id, parent)`` or
    None)."""
    span = task_id_hex[:16]
    if trace:
        return (trace[0], span, trace[1])
    return (task_id_hex[:32], span, None)


# ---- the recorder ----------------------------------------------------
class FlightRecorder:
    """Per-process bounded event ring. ``record()`` is the hot-path
    entry: one small dict + one deque append under a short lock;
    overflow drops the OLDEST event (counted). ``send`` ships drained
    batches (fire-and-forget — the runtime's flusher queue is
    non-blocking, and the wire message rides the reliable layer for
    exactly-once-effect at the controller)."""

    #: flush as soon as this many events are buffered (latency bound
    #: comes from the callers' periodic maybe_flush)
    WATERMARK = 256

    def __init__(self, proc: str, capacity: int = 4096,
                 send: Optional[Callable[[List[dict]], None]] = None,
                 interval_s: float = 1.0, enabled: bool = True):
        self.proc = proc
        self.pid = os.getpid()
        self.enabled = enabled
        self._send = send
        self._interval = interval_s
        self._cap = max(16, int(capacity))
        self._lock = threading.Lock()
        self._buf: "collections.deque[dict]" = collections.deque()
        self.dropped = 0
        self._last_flush = time.monotonic()
        self._dropped_metric = None

    # ------------------------------------------------------------ write
    def record(self, ev: str, task: Optional[Any] = None,
               trace: Optional[str] = None, span: Optional[str] = None,
               parent: Optional[str] = None, **data) -> None:
        if not self.enabled:
            return
        e: Dict[str, Any] = {"ev": ev, "ts": time.time(),
                             "proc": self.proc, "pid": self.pid}
        if task is not None:
            e["task"] = task.hex() if isinstance(task, bytes) else task
        if trace is not None:
            e["trace"] = trace
        if span is not None:
            e["span"] = span
        if parent is not None:
            e["parent"] = parent
        if data:
            e.update(data)
        flush_now = False
        with self._lock:
            self._buf.append(e)
            if len(self._buf) > self._cap:
                self._buf.popleft()
                self.dropped += 1
                self._count_drop_locked()
            flush_now = self._send is not None and \
                len(self._buf) >= self.WATERMARK
        if flush_now:
            self.flush()

    def record_task(self, ev: str, task_id_hex: str,
                    spec_trace: Optional[tuple], **data) -> None:
        """Record a lifecycle event with the trace triple resolved from
        a propagated ``TaskSpec.trace``."""
        t, s, p = task_trace(task_id_hex, spec_trace)
        self.record(ev, task=task_id_hex, trace=t, span=s, parent=p,
                    **data)

    def _count_drop_locked(self) -> None:
        m = self._dropped_metric
        if m is None:
            try:
                from ray_tpu.core.metric_defs import runtime_metrics
                m = self._dropped_metric = \
                    runtime_metrics().events_dropped.bound()
            except Exception:
                return
        try:
            m.inc()
        except Exception:
            pass

    # ------------------------------------------------------------ drain
    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def drain(self) -> List[dict]:
        """Take every buffered event WITHOUT sending (controller local
        ingest, tests, shutdown dumps)."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
            self._last_flush = time.monotonic()
        return out

    def flush(self) -> None:
        """Ship every buffered event through ``send`` now. Never raises
        and never blocks on the network: the send hook enqueues into
        the process's async flusher."""
        if self._send is None:
            return
        evs = self.drain()
        if not evs:
            return
        try:
            self._send(evs)
        except Exception:
            # boot/shutdown window: the transport isn't up — the events
            # are observability, losing a batch must not hurt the task
            pass

    def maybe_flush(self, now: Optional[float] = None) -> None:
        """Time-based flush (call from any periodic loop; cheap no-op
        inside the interval)."""
        if self._send is None or not self._buf:
            return
        if (now or time.monotonic()) - self._last_flush >= self._interval:
            self.flush()


def make_recorder(proc: str, config, send=None) -> FlightRecorder:
    """Build a process's recorder from config knobs."""
    return FlightRecorder(
        proc,
        capacity=getattr(config, "task_events_ring_size", 4096),
        send=send,
        interval_s=getattr(config, "task_events_report_interval_ms",
                           1000) / 1000.0,
        enabled=getattr(config, "enable_task_events", True))


# ---- Perfetto / Chrome-trace export ----------------------------------
def _flow_id(span: str) -> int:
    try:
        return int(span[:15] or "0", 16) or 1
    except ValueError:
        return 1


def build_chrome_trace(events: List[dict],
                       counters: Optional[List[dict]] = None,
                       requests: Optional[List[dict]] = None) -> dict:
    """Render merged flight-recorder events as Chrome-trace/Perfetto
    JSON: one track (pid) per recording process, ``X`` slices for each
    RUNNING→FINISHED/FAILED execution attempt, instants for the other
    events, and flow arrows (``s``/``f`` pairs keyed by the task's span
    id) from each SUBMITTED site to every execution of that task — so
    a trace id can be followed visually across processes, replays
    included.

    ``counters`` (optional) are pre-built ``"ph": "C"`` counter events
    from the fleet metrics plane
    (``metrics_plane.MetricsPlane.chrome_counters``): each carries a
    ``proc`` key naming its origin process and is re-homed onto that
    process's track, so tokens/s / queue-depth / occupancy curves
    render alongside the spans they explain.

    ``requests`` (optional) are request-trace waterfalls
    (``RequestTraceStore.waterfall`` shape): each renders as an async
    track of ``b``/``e`` pairs keyed by its request_id — one lane per
    request on a dedicated "requests" process — with a flow arrow from
    the waterfall into the producing engine process's slices (the
    ``procs`` map shipped with each span batch names the anchor track),
    so a slow request can be followed from its QUEUED lane straight
    into the engine/stage ticks that explain it."""
    procs: Dict[str, int] = {}
    trace_events: List[dict] = []

    def pid_for(proc: str) -> int:
        p = procs.get(proc)
        if p is None:
            p = procs[proc] = len(procs) + 1
            trace_events.append({
                "ph": "M", "name": "process_name", "pid": p, "tid": 0,
                "args": {"name": proc}})
        return p

    by_task: Dict[str, List[dict]] = {}
    for e in events:
        if not isinstance(e, dict) or "ev" not in e:
            continue
        pid_for(e.get("proc", "?"))
        t = e.get("task")
        if t is not None:
            by_task.setdefault(t, []).append(e)

    for task, evs in sorted(by_task.items()):
        evs.sort(key=lambda e: e.get("ts", 0.0))
        span = next((e["span"] for e in evs if e.get("span")),
                    task[:16])
        trace = next((e["trace"] for e in evs if e.get("trace")), None)
        fid = _flow_id(span)
        base_args = {"task_id": task, "trace_id": trace,
                     "span_id": span}
        name = next((e.get("name") for e in evs if e.get("name")),
                    None) or f"task:{task[:12]}"
        # RUNNING..FINISHED/FAILED slice pairs, per process (an attempt
        # that died unflushed leaves an open RUNNING — rendered as an
        # instant instead of a bogus slice)
        open_run: Dict[str, dict] = {}
        for e in evs:
            pid = pid_for(e.get("proc", "?"))
            ts_us = e.get("ts", 0.0) * 1e6
            ev = e["ev"]
            if ev == RUNNING:
                open_run[e.get("proc", "?")] = e
                continue
            if ev in (FINISHED, FAILED):
                start = open_run.pop(e.get("proc", "?"), None)
                if start is not None:
                    t0 = start.get("ts", 0.0) * 1e6
                    trace_events.append({
                        "name": name, "cat": "task", "ph": "X",
                        "ts": t0, "dur": max(1.0, ts_us - t0),
                        "pid": pid, "tid": 0,
                        "args": dict(base_args, outcome=ev,
                                     error=e.get("error"))})
                    # flow target: the submission arrow lands at the
                    # start of this execution slice
                    trace_events.append({
                        "name": "submit", "cat": "flow", "ph": "f",
                        "bp": "e", "id": fid, "ts": t0 + 1,
                        "pid": pid, "tid": 0})
                    continue
            if ev == SUBMITTED:
                # small slice so the flow arrow has a source anchor
                trace_events.append({
                    "name": f"submit {name}", "cat": "task", "ph": "X",
                    "ts": ts_us, "dur": 50.0, "pid": pid, "tid": 0,
                    "args": dict(base_args, parent=e.get("parent"))})
                trace_events.append({
                    "name": "submit", "cat": "flow", "ph": "s",
                    "id": fid, "ts": ts_us + 1, "pid": pid, "tid": 0})
                continue
            args = dict(base_args)
            args.update({k: v for k, v in e.items()
                         if k not in ("ev", "ts", "proc", "pid", "task",
                                      "trace", "span", "parent")})
            trace_events.append({
                "name": ev if ev != YIELDED
                else f"yield[{e.get('index')}]",
                "cat": "task_event", "ph": "i", "s": "t",
                "ts": ts_us, "pid": pid, "tid": 0, "args": args})
        for proc, start in open_run.items():
            trace_events.append({
                "name": f"{name} (unfinished)", "cat": "task_event",
                "ph": "i", "s": "t", "ts": start.get("ts", 0.0) * 1e6,
                "pid": pid_for(proc), "tid": 0, "args": base_args})

    # transport / untasked events land on their process track. Events
    # carrying a duration (STAGE_TICK forward/backward/idle intervals)
    # render as X slices ending at their record timestamp — laid side
    # by side per process they ARE the pipeline schedule, and the gaps
    # plus the phase="idle" slices are the measured bubbles.
    for e in events:
        if not isinstance(e, dict) or "ev" not in e:
            continue
        if e.get("task") is not None:
            continue
        args = {k: v for k, v in e.items()
                if k not in ("ev", "ts", "proc", "pid")}
        dur_s = e.get("dur_s")
        if isinstance(dur_s, (int, float)) and dur_s > 0:
            name = e["ev"]
            if e.get("phase"):
                name = f"{name}:{e['phase']}"
                if e.get("mb") is not None:
                    name += f"[{e['mb']}]"
                if e.get("vs") is not None:
                    # virtual-stage (chunk) index: separates the
                    # interleaved chunks' occupancy on one stage track
                    name += f"@c{e['vs']}"
            trace_events.append({
                "name": name, "cat": "stage", "ph": "X",
                "ts": (e.get("ts", 0.0) - dur_s) * 1e6,
                "dur": max(1.0, dur_s * 1e6),
                "pid": pid_for(e.get("proc", "?")), "tid": 0,
                "args": args})
            continue
        trace_events.append({
            "name": e["ev"], "cat": "transport", "ph": "i", "s": "t",
            "ts": e.get("ts", 0.0) * 1e6,
            "pid": pid_for(e.get("proc", "?")), "tid": 0,
            "args": args})

    for c in counters or ():
        if not isinstance(c, dict) or c.get("ph") != "C":
            continue
        e = dict(c)
        proc = e.pop("proc", None)
        if proc is not None:
            e["pid"] = pid_for(proc)
        trace_events.append(e)

    # request waterfalls: one async lane per request id on a shared
    # "requests" process track
    for w in requests or ():
        if not isinstance(w, dict) or not w.get("request_id"):
            continue
        rid = w["request_id"]
        spans = [s for s in (w.get("spans") or ())
                 if isinstance(s, dict)]
        if not spans:
            continue
        rpid = pid_for("requests")
        for s in spans:
            t0_us = s.get("t0", 0.0) * 1e6
            t1_us = max(s.get("t1", 0.0) * 1e6, t0_us + 1.0)
            args = dict(s.get("attrs") or {}, request_id=rid)
            phase = s.get("phase", "?")
            trace_events.append({
                "name": phase, "cat": "request", "ph": "b",
                "id": rid, "ts": t0_us, "pid": rpid, "tid": 0,
                "args": args})
            trace_events.append({
                "name": phase, "cat": "request", "ph": "e",
                "id": rid, "ts": t1_us, "pid": rpid, "tid": 0})
        # flow arrow into the engine process's slices: source at the
        # waterfall's first engine-side span, target on the engine
        # track at the same instant (lands on whatever ENGINE_STATS /
        # stage-tick slice is active there)
        engine_proc = (w.get("procs") or {}).get("engine")
        anchor = next((s for s in spans
                       if s.get("phase") in ("ADMITTED", "PREFILL",
                                             "DECODE", "FIRST_TOKEN")),
                      None)
        if engine_proc and anchor is not None:
            fid = _flow_id(rid.rpartition("-")[2])
            ts_us = anchor.get("t0", 0.0) * 1e6
            trace_events.append({
                "name": "request", "cat": "flow", "ph": "s",
                "id": fid, "ts": ts_us + 1, "pid": rpid, "tid": 0})
            trace_events.append({
                "name": "request", "cat": "flow", "ph": "f",
                "bp": "e", "id": fid, "ts": ts_us + 2,
                "pid": pid_for(engine_proc), "tid": 0})

    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": {"source": "ray_tpu flight recorder",
                          "processes": {v: k for k, v in procs.items()}}}
