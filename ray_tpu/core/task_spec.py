"""Task and actor specifications — the unit of scheduling.

Equivalent of the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h``) minus protobuf: a plain dataclass
carried over the control plane. Functions/classes are NOT embedded; they are
exported once to the controller's function store keyed by a
``FunctionDescriptor`` (reference: ``python/ray/_private/function_manager.py``)
and loaded lazily (and cached) by workers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)


#: ``num_returns`` sentinel for streaming generator tasks (the API-level
#: ``num_returns="streaming"``): return objects are minted dynamically,
#: one per yielded item, and reported via STREAM_ITEM while the task
#: runs (reference: TaskSpec returns_dynamically / num_streaming_returns)
STREAMING_RETURNS = -1


@dataclass(frozen=True, slots=True)
class FunctionDescriptor:
    """Stable key for a remote function / actor class."""
    module: str
    qualname: str
    function_hash: str  # sha1 of the pickled function

    def key(self) -> str:
        return f"{self.module}.{self.qualname}:{self.function_hash}"

    def __repr__(self):
        return f"Fn({self.module}.{self.qualname})"

    def __reduce__(self):
        # positional wire form: dataclass pickling writes every field
        # NAME per message; specs ride the per-task hot path, so the
        # names are pure overhead (reference keeps specs in protobuf
        # for the same reason)
        return (FunctionDescriptor,
                (self.module, self.qualname, self.function_hash))


@dataclass(slots=True)
class SchedulingStrategy:
    """Union of the reference's scheduling strategies
    (python/ray/util/scheduling_strategies.py)."""
    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP | NODE_LABEL
    node_id: Optional[NodeID] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False
    hard_labels: Dict[str, List[str]] = field(default_factory=dict)
    soft_labels: Dict[str, List[str]] = field(default_factory=dict)

    def __reduce__(self):
        # DEFAULT strategy (the overwhelmingly common case) pickles as a
        # zero-arg call; everything else rides positionally
        if self.kind == "DEFAULT" and self.node_id is None \
                and not self.hard_labels and not self.soft_labels \
                and self.placement_group_id is None:
            return (SchedulingStrategy, ())
        return (SchedulingStrategy, (
            self.kind, self.node_id, self.soft, self.placement_group_id,
            self.placement_group_bundle_index,
            self.placement_group_capture_child_tasks,
            self.hard_labels, self.soft_labels))


@dataclass(slots=True)
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    function: FunctionDescriptor
    # Serialized args blob (SerializedObject wire bytes); refs are passed
    # positionally via arg_refs and substituted at execution time.
    args_blob: bytes = b""
    arg_refs: List[Tuple[int, ObjectID]] = field(default_factory=list)
    num_returns: int = 1
    #: owner-known metadata for arg objects (inline blob / location),
    #: attached at submission so the controller can satisfy dependencies
    #: it never heard about (producer died with TASK_DONE unflushed; the
    #: owner still got its direct TASK_RESULT)
    arg_metas: Optional[Dict[bytes, dict]] = None
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 3
    retry_exceptions: bool = False
    owner: Optional[WorkerID] = None
    name: str = ""
    runtime_env: Optional[dict] = None
    # actor task fields
    actor_id: Optional[ActorID] = None
    sequence_number: int = -1
    concurrency_group: str = ""
    # actor creation fields
    is_actor_creation: bool = False
    #: Reference semantics: by default an actor needs 1 CPU to *schedule*
    #: but holds 0 while alive (python/ray/actor.py default num_cpus); only
    #: explicitly requested resources (TPU, custom) are held for life.
    hold_resources: bool = True
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    max_pending_calls: int = -1
    actor_name: str = ""
    namespace: str = ""
    is_async_actor: bool = False
    #: streaming-only: per-call backpressure window override
    #: (0 = use config.generator_backpressure_num_objects; <0 = off)
    backpressure: int = 0
    #: causal trace propagation (core/events.py): ``(trace_id,
    #: parent_span)`` hex pair stamped at submission; the task's own
    #: span id is derived from its task id. Rides every spec-carrying
    #: control message (DSP/ASG/ACL/CAC) so the flight recorder links
    #: parent -> child across processes.
    trace: Optional[Tuple[str, Optional[str]]] = None

    @property
    def is_actor_task(self) -> bool:
        return self.actor_id is not None and not self.is_actor_creation

    @property
    def is_streaming(self) -> bool:
        return self.num_returns == STREAMING_RETURNS

    def return_ids(self) -> List[ObjectID]:
        # streaming tasks (num_returns == STREAMING_RETURNS == -1) have
        # no static returns: the empty range is load-bearing
        return [ObjectID.for_task_return(self.task_id, i + 1)
                for i in range(self.num_returns)]

    def __reduce__(self):
        # Positional wire form (same rationale as FunctionDescriptor):
        # a spec crosses at least two process boundaries per task, and
        # dataclass pickling ships all ~25 field names each time —
        # ~3x the bytes and ~3x the CPU of this tuple.
        return (_spec_from_wire, (
            self.task_id, self.job_id, self.function, self.args_blob,
            self.arg_refs, self.num_returns, self.arg_metas,
            self.resources, self.scheduling_strategy, self.max_retries,
            self.retry_exceptions, self.owner, self.name,
            self.runtime_env, self.actor_id, self.sequence_number,
            self.concurrency_group, self.is_actor_creation,
            self.hold_resources, self.max_restarts,
            self.max_task_retries, self.max_concurrency,
            self.max_pending_calls, self.actor_name, self.namespace,
            self.is_async_actor, self.backpressure, self.trace))


def _spec_from_wire(*fields) -> "TaskSpec":
    return TaskSpec(*fields)


@dataclass
class Bundle:
    """A placement-group bundle: an atomic resource reservation
    (reference: src/ray/common/bundle_spec.h)."""
    resources: Dict[str, float]
    node_id: Optional[NodeID] = None  # filled after placement


@dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    #: PACK | SPREAD | STRICT_PACK | STRICT_SPREAD, plus the TPU gang
    #: pair SLICE_PACK | SLICE_SPREAD (all bundles on hosts of ONE
    #: slice; SPREAD = one bundle per distinct host — see
    #: core/scheduler.py::_plan_slice_bundles)
    strategy: str = "PACK"
    name: str = ""
    creator_job: Optional[JobID] = None


@dataclass
class ActorInfo:
    """Controller-side actor directory entry (reference:
    gcs_actor_manager.h actor state machine :249-281)."""
    actor_id: ActorID
    spec: TaskSpec
    state: str = "PENDING"  # PENDING|STARTING|ALIVE|RESTARTING|DEAD
    node_id: Optional[NodeID] = None
    worker_id: Optional[WorkerID] = None
    num_restarts: int = 0
    name: str = ""
    namespace: str = ""
    death_cause: str = ""
