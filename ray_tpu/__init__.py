"""ray_tpu: a TPU-native distributed AI framework.

A brand-new, TPU-first framework with the capabilities of Ray (reference:
``python/ray/__init__.py``): tasks, actors, a shared-memory object store with
reference counting and lineage recovery, placement groups, collectives whose
accelerator backend is XLA/ICI (not NCCL), and AI libraries on top (train,
tune, data, serve, rllib).

Design stance (see SURVEY.md §7): the programming model is Ray-shaped; the
unit of accelerator scheduling is the TPU pod slice and the unit of numerics
is a jitted GSPMD program.
"""

from ray_tpu._version import __version__
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.streaming import ObjectRefGenerator, wait_any
from ray_tpu.actor import ActorClass, ActorHandle, ActorMethod
from ray_tpu.api import (
    init,
    shutdown,
    is_initialized,
    remote,
    get,
    put,
    wait,
    kill,
    cancel,
    get_actor,
    method,
    nodes,
    cluster_resources,
    available_resources,
    get_runtime_context,
    timeline,
)
from ray_tpu.exceptions import (
    RayTpuError,
    TaskError,
    ActorError,
    ActorDiedError,
    ActorUnavailableError,
    ObjectLostError,
    TaskCancelledError,
    OutOfMemoryError,
    GetTimeoutError,
    RpcTimeoutError,
    DeliveryFailedError,
    StreamCancelledError,
    AdmissionRejectedError,
)
from ray_tpu.runtime_context import RuntimeContext

# Subpackages are imported lazily to keep `import ray_tpu` light; heavy
# libraries (train/tune/data/serve/rllib) pull in jax on import.
from ray_tpu import util  # noqa: F401

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "method",
    "nodes",
    "cluster_resources",
    "available_resources",
    "get_runtime_context",
    "timeline",
    "ObjectRef",
    "ObjectRefGenerator",
    "wait_any",
    "ActorClass",
    "ActorHandle",
    "ActorMethod",
    "RuntimeContext",
    "RayTpuError",
    "TaskError",
    "ActorError",
    "ActorDiedError",
    "ActorUnavailableError",
    "ObjectLostError",
    "TaskCancelledError",
    "OutOfMemoryError",
    "GetTimeoutError",
    "RpcTimeoutError",
    "AdmissionRejectedError",
    "DeliveryFailedError",
    "StreamCancelledError",
]
