#!/usr/bin/env python
"""Serving benchmark: continuous-batching tokens/s/chip under a
synthetic many-client load (the SERVE metric, gated by
``tools/perf_gate.py --metric serve``).

Prints ONE JSON line:
``{"metric": "serve_tokens_per_s_chip", "value", "unit", "vs_serial",
"detail"}``.

Workload: ``--clients`` concurrent clients replay a seeded schedule of
``--requests`` requests with Poisson arrivals and sampled prompt/output
lengths against a Serve deployment of :class:`ray_tpu.serve.LLMServer`,
each consuming its token stream through
``handle.options(stream=True)`` — the full engine + streaming +
reliable-delivery path, not a model-only microbench. The same schedule
then replays against a ``decode_slots=1`` engine (serial per-request
decode, everything else identical): ``vs_serial`` is the
continuous-batching speedup, the headline claim of the engine.

Reported: tokens/s/chip (headline), TTFT p50/p99, inter-token latency
p50/p99, the engine's batch-occupancy histogram, and the engine/model
config that produced them. ``--smoke`` shrinks everything for CI.

**Fleet mode** (``detail.fleet``): the same harness against an
N-replica deployment under a many-client Poisson load where every
prompt opens with a COMMON system prompt (>= 4 KV blocks long — the
high-traffic shape prefix sharing exists for), with prompt-lookup
speculative decode on and the handle's gauge-aware routing; then the
identical schedule replays against a fleet with sharing+speculation
OFF and round-robin routing (the pre-PR baseline). Emits fleet
tokens/s/chip, fleet p99 TTFT, the aggregate prefix hit rate, the
speculation acceptance rate, and ``vs_baseline`` — the fleet rows
gated by ``tools/perf_gate.py --metric serve``.

On TPU the model is sized up with the chip; on CPU a tiny config keeps
the harness runnable anywhere (the CPU record is a smoke point for the
serve series, like the CPU BENCH records).
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
from typing import Dict, List, Optional


def _percentile(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    i = min(int(p / 100.0 * len(xs)), len(xs) - 1)
    return xs[i]


def make_workload(n_requests: int, clients: int, seed: int,
                  mean_interarrival_s: float,
                  prompt_rng=(4, 48), out_rng=(8, 32),
                  system_prompt: Optional[List[int]] = None) -> List[dict]:
    """Seeded request schedule: Poisson arrivals (exponential
    inter-arrival gaps), uniform prompt/output lengths. The SAME
    schedule replays against both engine modes. ``system_prompt``
    (fleet mode) is prepended to every request's sampled tail — the
    shared-prefix traffic shape."""
    rng = random.Random(seed)
    sys_p = list(system_prompt or [])
    t = 0.0
    reqs = []
    for i in range(n_requests):
        t += rng.expovariate(1.0 / mean_interarrival_s)
        plen = rng.randint(*prompt_rng)
        reqs.append({
            "arrival_s": t,
            "prompt": sys_p + [rng.randrange(2, 128)
                               for _ in range(plen)],
            "max_new_tokens": rng.randint(*out_rng),
            "client": i % clients,
        })
    return reqs


def run_load(handle_factory, workload: List[dict], clients: int,
             timeout_s: float = 600.0,
             handle_opts: Optional[Dict] = None) -> Dict:
    """Replay the schedule with one thread + one handle per client;
    per-request TTFT / inter-token gaps are recorded client-side (what
    a user of the HTTP proxy would observe). ``handle_opts`` are extra
    ``handle.options`` (fleet mode: ``routing_policy``)."""
    per_client: Dict[int, List[dict]] = {c: [] for c in range(clients)}
    for r in workload:
        per_client[r["client"]].append(r)
    results: List[dict] = []
    errors: List[str] = []
    lock = threading.Lock()
    opts = dict(handle_opts or {})
    t0 = time.monotonic()

    def client_loop(cid: int):
        handle = handle_factory()
        for r in per_client[cid]:
            delay = r["arrival_s"] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            rec = {"client": cid, "tokens": 0}
            t_submit = time.monotonic()
            try:
                gen = handle.options(stream=True, **opts).generate.remote(
                    r["prompt"], r["max_new_tokens"])
                prev = None
                gaps = []
                for _tok in gen:
                    now = time.monotonic()
                    if prev is None:
                        rec["ttft_s"] = now - t_submit
                    else:
                        gaps.append(now - prev)
                    prev = now
                    rec["tokens"] += 1
                rec["gaps"] = gaps
                rec["t_last"] = prev if prev is not None else t_submit
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                results.append(rec)

    threads = [threading.Thread(target=client_loop, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout_s
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.1))
    if any(t.is_alive() for t in threads):
        errors.append("client threads timed out")
    total_tokens = sum(r["tokens"] for r in results)
    t_last = max((r["t_last"] for r in results), default=t0)
    wall = max(t_last - t0, 1e-9)
    ttfts = [r["ttft_s"] for r in results if "ttft_s" in r]
    gaps = [g for r in results for g in r.get("gaps", ())]
    return {
        "tokens_total": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 2),
        "requests_done": len(results),
        "ttft_ms": {"p50": _ms(_percentile(ttfts, 50)),
                    "p99": _ms(_percentile(ttfts, 99))},
        "inter_token_ms": {"p50": _ms(_percentile(gaps, 50)),
                           "p99": _ms(_percentile(gaps, 99))},
        "errors": errors,
    }


def _ms(v: Optional[float]) -> Optional[float]:
    return round(v * 1e3, 2) if v is not None else None


def _fleet_leg(name: str, model: Dict, engine: Dict, workload: List[dict],
               clients: int, replicas: int, policy: str,
               timeout_s: float = 600.0) -> Dict:
    """One fleet measurement: deploy ``replicas`` copies, warm every
    replica's jitted programs round-robin outside the window, replay
    the schedule with ``policy`` routing, and fold in the per-replica
    engine counters (prefix hits, speculation acceptance)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    dep = serve.deployment(
        name=name, num_replicas=replicas,
        max_ongoing_requests=4 * clients + 8)(serve.LLMServer)
    serve.run(dep.bind(model=model, engine=engine), name=name)
    handle = serve.get_app_handle(name)
    for _ in range(replicas):
        list(handle.options(
            stream=True, routing_policy="round_robin").generate.remote(
                workload[0]["prompt"][:4], 2))
    load = run_load(lambda: serve.get_app_handle(name), workload,
                    clients, timeout_s=timeout_s,
                    handle_opts={"routing_policy": policy})
    ctrl = serve_api._controller_or_none()
    reps = ray_tpu.get(ctrl.get_replicas.remote(name))
    stats = [ray_tpu.get(r.stats.remote(), timeout=60) for r in reps]
    engines = [s.get("engine") or {} for s in stats]
    hit = sum(e.get("prefix_hit_blocks_total") or 0 for e in engines)
    pblocks = sum(e.get("prompt_blocks_total") or 0 for e in engines)
    drafted = sum((e.get("spec") or {}).get("drafted") or 0
                  for e in engines)
    accepted = sum((e.get("spec") or {}).get("accepted") or 0
                   for e in engines)
    serve.delete(name)
    return {
        "replicas": replicas,
        "routing": policy,
        "tokens_per_s": load["tokens_per_s"],
        "tokens_per_s_chip": round(load["tokens_per_s"] / replicas, 2),
        "ttft_ms": load["ttft_ms"],
        "inter_token_ms": load["inter_token_ms"],
        "wall_s": load["wall_s"],
        "tokens_total": load["tokens_total"],
        "requests_done": load["requests_done"],
        "errors": load["errors"],
        "prefix_hit_blocks": hit,
        "prompt_blocks": pblocks,
        "prefix_hit_rate": round(hit / pblocks, 4) if pblocks else None,
        "spec_drafted": drafted,
        "spec_accepted": accepted,
        "spec_acceptance": (round(accepted / drafted, 4)
                            if drafted else None),
        "per_replica_tokens": [e.get("tokens_total") for e in engines],
    }


def bench_fleet(model: Dict, engine: Dict, replicas: int, clients: int,
                requests: int, seed: int, sys_prompt_tokens: int,
                prompt_rng, out_rng, mean_interarrival_s: float,
                timeout_s: float = 600.0) -> Dict:
    """The fleet comparison: prefix sharing + prompt-lookup speculation
    + gauge routing vs the sharing-off / speculation-off / round-robin
    baseline on the SAME seeded schedule. Every prompt opens with one
    common system prompt ``sys_prompt_tokens`` long (>= 4 KV blocks)."""
    rng = random.Random(seed + 1)
    system_prompt = [rng.randrange(2, 128)
                     for _ in range(sys_prompt_tokens)]
    workload = make_workload(requests, clients, seed,
                             mean_interarrival_s=mean_interarrival_s,
                             prompt_rng=prompt_rng, out_rng=out_rng,
                             system_prompt=system_prompt)
    eng_on = dict(engine, enable_prefix_sharing=True, spec_tokens=4)
    eng_off = dict(engine, enable_prefix_sharing=False, spec_tokens=0)
    fleet = _fleet_leg("llm_fleet", model, eng_on, workload, clients,
                       replicas, policy="gauge", timeout_s=timeout_s)
    base = _fleet_leg("llm_fleet_base", model, eng_off, workload,
                      clients, replicas, policy="round_robin",
                      timeout_s=timeout_s)
    fleet["system_prompt_tokens"] = sys_prompt_tokens
    fleet["clients"] = clients
    fleet["requests"] = requests
    fleet["baseline"] = base
    fleet["vs_baseline"] = (
        round(fleet["tokens_per_s_chip"] / base["tokens_per_s_chip"], 2)
        if base["tokens_per_s_chip"] else None)
    return fleet


def bench(smoke: bool = False, clients: int = 8, requests: int = 24,
          seed: int = 0, fleet_replicas: int = 0,
          fleet_clients: int = 0, fleet_requests: int = 0) -> dict:
    import jax

    import ray_tpu
    from ray_tpu import serve

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    if smoke:
        clients, requests = min(clients, 4), min(requests, 6)
        model = {"vocab_size": 128, "d_model": 32, "n_layers": 2,
                 "n_heads": 4, "head_dim": 8, "d_ff": 64,
                 "max_seq_len": 128, "rotary_dim": 8, "dtype": "float32",
                 "remat_policy": "none"}
        engine = {"decode_slots": clients, "kv_block_size": 8,
                  "max_seq_len": 64, "prefill_chunk": 16}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.02,
                                 prompt_rng=(4, 12), out_rng=(6, 10))
        fleet_kw = dict(replicas=fleet_replicas or 2,
                        clients=fleet_clients or 6,
                        requests=fleet_requests or 12,
                        sys_prompt_tokens=4 * engine["kv_block_size"],
                        prompt_rng=(2, 6), out_rng=(6, 10),
                        mean_interarrival_s=0.02, timeout_s=120.0)
    elif on_tpu:
        model = {"vocab_size": 32000, "d_model": 2048, "n_layers": 8,
                 "n_heads": 16, "head_dim": 128, "d_ff": 8192,
                 "max_seq_len": 2048, "rotary_dim": 64,
                 "dtype": "bfloat16", "remat_policy": "none"}
        engine = {"decode_slots": 32, "kv_block_size": 32,
                  "max_seq_len": 1024, "prefill_chunk": 256}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.05,
                                 prompt_rng=(32, 512), out_rng=(32, 128))
        fleet_kw = dict(replicas=fleet_replicas or 4,
                        clients=fleet_clients or 200,
                        requests=fleet_requests or 400,
                        sys_prompt_tokens=4 * engine["kv_block_size"],
                        prompt_rng=(16, 128), out_rng=(32, 128),
                        mean_interarrival_s=0.02)
    else:
        # CPU sizing: wide enough that a decode step is weight-stream /
        # gemv bound, so step cost is nearly batch-independent — the
        # same regime a real chip is in at decode batch 1 (MXU idle),
        # which is what continuous batching amortizes. Arrivals are
        # compressed so the queue saturates the slots (the serial
        # baseline queues identically).
        model = {"vocab_size": 1024, "d_model": 256, "n_layers": 2,
                 "n_heads": 4, "head_dim": 32, "d_ff": 1024,
                 "max_seq_len": 256, "rotary_dim": 16,
                 "dtype": "float32", "remat_policy": "none"}
        engine = {"decode_slots": clients, "kv_block_size": 16,
                  "max_seq_len": 128, "prefill_chunk": 32}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.005,
                                 prompt_rng=(8, 24), out_rng=(24, 48))
        fleet_kw = dict(replicas=fleet_replicas or 2,
                        clients=fleet_clients or 32,
                        requests=fleet_requests or 64,
                        sys_prompt_tokens=4 * engine["kv_block_size"],
                        prompt_rng=(4, 16), out_rng=(16, 32),
                        mean_interarrival_s=0.01)

    ray_tpu.init(num_cpus=max(8, clients + 4,
                              fleet_kw["clients"] // 2 + 6),
                 _num_initial_workers=3, ignore_reinit_error=True)
    modes = {}
    stats = {}
    try:
        for mode, slots in (("continuous", engine["decode_slots"]),
                            ("serial", 1)):
            ecfg = dict(engine, decode_slots=slots)
            name = f"llm_{mode}"
            dep = serve.deployment(
                name=name, max_ongoing_requests=4 * clients + 8)(
                    serve.LLMServer)
            serve.run(dep.bind(model=model, engine=ecfg), name=name)
            handle = serve.get_app_handle(name)
            # one throwaway request compiles prefill+decode outside the
            # measured window (admission itself never recompiles)
            list(handle.options(stream=True).generate.remote(
                workload[0]["prompt"][:4], 2))
            modes[mode] = run_load(
                lambda name=name: serve.get_app_handle(name),
                workload, clients)
            stats[mode] = handle.stats.remote().result(timeout_s=60)
            serve.delete(name)
        # fleet leg: shared system prompt, gauge routing, prefix
        # sharing + speculation vs the round-robin no-sharing baseline
        t_fleet = time.monotonic()
        fleet = bench_fleet(model, engine, seed=seed, **fleet_kw)
        fleet["leg_wall_s"] = round(time.monotonic() - t_fleet, 2)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()

    cont, ser = modes["continuous"], modes["serial"]
    n_chips = 1   # the engine decodes on one device
    vs_serial = (round(cont["tokens_per_s"] / ser["tokens_per_s"], 2)
                 if ser["tokens_per_s"] else None)
    return {
        "metric": "serve_tokens_per_s_chip",
        "value": round(cont["tokens_per_s"] / n_chips, 2),
        "unit": "tokens/s/chip",
        "vs_serial": vs_serial,
        "detail": {
            "backend": backend,
            "n_chips": n_chips,
            "clients": clients,
            "requests": requests,
            "seed": seed,
            "model": model,
            "engine": engine,
            "continuous": cont,
            "serial": ser,
            "occupancy_hist": stats["continuous"].get("occupancy_hist"),
            "engine_stats": {m: {k: s.get(k) for k in
                                 ("tokens_total", "decode_steps",
                                  "prefill_chunks", "free_blocks",
                                  "total_blocks")}
                             for m, s in stats.items()},
            "fleet": fleet,
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny workload for CI (subprocess smoke test)")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fleet-replicas", type=int, default=0,
                    help="fleet-leg replica count (0 = per-backend "
                         "default: 2 CPU / 4 TPU)")
    ap.add_argument("--fleet-clients", type=int, default=0,
                    help="fleet-leg Poisson clients (0 = default)")
    ap.add_argument("--fleet-requests", type=int, default=0,
                    help="fleet-leg request count (0 = default)")
    args = ap.parse_args()
    rec = bench(smoke=args.smoke, clients=args.clients,
                requests=args.requests, seed=args.seed,
                fleet_replicas=args.fleet_replicas,
                fleet_clients=args.fleet_clients,
                fleet_requests=args.fleet_requests)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
