#!/usr/bin/env python
"""Serving benchmark: continuous-batching tokens/s/chip under a
synthetic many-client load (the SERVE metric, gated by
``tools/perf_gate.py --metric serve``).

Prints ONE JSON line:
``{"metric": "serve_tokens_per_s_chip", "value", "unit", "vs_serial",
"detail"}``.

Workload: ``--clients`` concurrent clients replay a seeded schedule of
``--requests`` requests with Poisson arrivals and sampled prompt/output
lengths against a Serve deployment of :class:`ray_tpu.serve.LLMServer`,
each consuming its token stream through
``handle.options(stream=True)`` — the full engine + streaming +
reliable-delivery path, not a model-only microbench. The same schedule
then replays against a ``decode_slots=1`` engine (serial per-request
decode, everything else identical): ``vs_serial`` is the
continuous-batching speedup, the headline claim of the engine.

Reported: tokens/s/chip (headline), TTFT p50/p99, inter-token latency
p50/p99, the engine's batch-occupancy histogram, and the engine/model
config that produced them. ``--smoke`` shrinks everything for CI.

**Fleet mode** (``detail.fleet``): the same harness against an
N-replica deployment under a many-client Poisson load where every
prompt opens with a COMMON system prompt (>= 4 KV blocks long — the
high-traffic shape prefix sharing exists for), with prompt-lookup
speculative decode on and the handle's gauge-aware routing; then the
identical schedule replays against a fleet with sharing+speculation
OFF and round-robin routing (the pre-PR baseline). Emits fleet
tokens/s/chip, fleet p99 TTFT, the aggregate prefix hit rate, the
speculation acceptance rate, and ``vs_baseline`` — the fleet rows
gated by ``tools/perf_gate.py --metric serve``.

**Paged-kernel legs** (``detail.paged_kernel`` / ``detail.mixed_len``):
the Pallas paged-attention kernel vs the XLA gather reference on one
mixed-length batch — exact parity (fp32-softmax tolerance) plus the
page-count work reduction that per-sequence length skipping buys
(FLOPs ∝ live tokens; on TPU the compiled kernel is also wall-clocked
against the reference, on CPU the kernel runs in interpret mode so
only the work accounting is meaningful) — and a live mixed short+long
engine run reporting ``decode_block_work_frac`` (pages touched / window
pages) and the engine's per-step prefill/decode device-wall split.

**Disaggregated prefill/decode** (``detail.disagg`` /
``detail.migration`` / ``detail.disagg_parity``): 1 prefill + 1 decode
replica with the KV-block hand-off shipping packed slabs between them
vs 2 colocated replicas at equal chip count, on a seeded
long-prefill/short-decode schedule — emits disagg tokens/s/chip, p99
TTFT, decode-slot occupancy, and the measured hand-off cost (KV bytes
+ wall per shipped request). ``detail.migration`` is the drain A/B: a
warmed victim's radix-trie chains migrate to one survivor and not the
other, and the same single-pass replay must score a strictly higher
prefix hit rate on the migrated survivor. ``detail.disagg_parity``
asserts greedy decode is bit-identical disagg on vs off on the exact
``bf16`` wire.

**Autoscaling under load** (``detail.scale_up``, ``--scale-up-mid-load``):
a deliberately backlogged single replica must scale up MID-RUN off its
engine gauges; the leg asserts routed traffic reaches the new replica
(``new_replica_share``) and records TTFT recovery against the same
schedule on a pinned 1-replica fleet (recovery > 1 needs one chip per
replica — on a shared CPU core a second replica only time-slices).

On TPU the model is sized up with the chip; on CPU a tiny config keeps
the harness runnable anywhere (the CPU record is a smoke point for the
serve series, like the CPU BENCH records).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import threading
import time
from typing import Dict, List, Optional


def _percentile(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    i = min(int(p / 100.0 * len(xs)), len(xs) - 1)
    return xs[i]


def make_workload(n_requests: int, clients: int, seed: int,
                  mean_interarrival_s: float,
                  prompt_rng=(4, 48), out_rng=(8, 32),
                  system_prompt: Optional[List[int]] = None) -> List[dict]:
    """Seeded request schedule: Poisson arrivals (exponential
    inter-arrival gaps), uniform prompt/output lengths. The SAME
    schedule replays against both engine modes. ``system_prompt``
    (fleet mode) is prepended to every request's sampled tail — the
    shared-prefix traffic shape."""
    rng = random.Random(seed)
    sys_p = list(system_prompt or [])
    t = 0.0
    reqs = []
    for i in range(n_requests):
        t += rng.expovariate(1.0 / mean_interarrival_s)
        plen = rng.randint(*prompt_rng)
        reqs.append({
            "arrival_s": t,
            "prompt": sys_p + [rng.randrange(2, 128)
                               for _ in range(plen)],
            "max_new_tokens": rng.randint(*out_rng),
            "client": i % clients,
        })
    return reqs


def run_load(handle_factory, workload: List[dict], clients: int,
             timeout_s: float = 600.0,
             handle_opts: Optional[Dict] = None) -> Dict:
    """Replay the schedule with one thread + one handle per client;
    per-request TTFT / inter-token gaps are recorded client-side (what
    a user of the HTTP proxy would observe). ``handle_opts`` are extra
    ``handle.options`` (fleet mode: ``routing_policy``)."""
    per_client: Dict[int, List[dict]] = {c: [] for c in range(clients)}
    for r in workload:
        per_client[r["client"]].append(r)
    results: List[dict] = []
    errors: List[str] = []
    lock = threading.Lock()
    opts = dict(handle_opts or {})
    t0 = time.monotonic()

    def client_loop(cid: int):
        handle = handle_factory()
        for r in per_client[cid]:
            delay = r["arrival_s"] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            rec = {"client": cid, "tokens": 0}
            t_submit = time.monotonic()
            rec["t_submit_s"] = t_submit - t0
            try:
                gen = handle.options(stream=True, **opts).generate.remote(
                    r["prompt"], r["max_new_tokens"])
                prev = None
                gaps = []
                for _tok in gen:
                    now = time.monotonic()
                    if prev is None:
                        rec["ttft_s"] = now - t_submit
                    else:
                        gaps.append(now - prev)
                    prev = now
                    rec["tokens"] += 1
                rec["gaps"] = gaps
                rec["t_last"] = prev if prev is not None else t_submit
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                results.append(rec)

    threads = [threading.Thread(target=client_loop, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout_s
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.1))
    if any(t.is_alive() for t in threads):
        errors.append("client threads timed out")
    total_tokens = sum(r["tokens"] for r in results)
    t_last = max((r["t_last"] for r in results), default=t0)
    wall = max(t_last - t0, 1e-9)
    ttfts = [r["ttft_s"] for r in results if "ttft_s" in r]
    gaps = [g for r in results for g in r.get("gaps", ())]
    # submit-ordered (t_submit_s, ttft_s) pairs: the scale-up leg reads
    # early-vs-late TTFT off this series (compact — no per-token gaps)
    series = sorted(
        ((round(r["t_submit_s"], 3), round(r["ttft_s"], 4))
         for r in results if "ttft_s" in r))
    return {
        "tokens_total": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 2),
        "requests_done": len(results),
        "ttft_ms": {"p50": _ms(_percentile(ttfts, 50)),
                    "p99": _ms(_percentile(ttfts, 99))},
        "inter_token_ms": {"p50": _ms(_percentile(gaps, 50)),
                           "p99": _ms(_percentile(gaps, 99))},
        "ttft_series": series,
        "errors": errors,
    }


def _ms(v: Optional[float]) -> Optional[float]:
    return round(v * 1e3, 2) if v is not None else None


# ------------------------------------------------- paged-kernel legs
def make_mixed_workload(n_requests: int, clients: int, seed: int,
                        engine: Dict,
                        mean_interarrival_s: float = 0.01) -> List[dict]:
    """Short+long requests sharing decode slots — the traffic shape
    length-aware block skipping exists for: alternate requests either
    stop after a few tokens or decode out to the engine window, so at
    any decode step the slot array holds wildly different live lengths
    while the XLA reference pays the full window for every slot."""
    rng = random.Random(seed)
    window = engine["max_seq_len"]
    t = 0.0
    reqs = []
    for i in range(n_requests):
        t += rng.expovariate(1.0 / mean_interarrival_s)
        plen = rng.randint(4, 8)
        long = i % 2 == 1
        out = (window - plen - 2) if long else rng.randint(4, 8)
        reqs.append({
            "arrival_s": t,
            "prompt": [rng.randrange(2, 128) for _ in range(plen)],
            "max_new_tokens": max(2, out),
            "client": i % clients,
            "long": long,
        })
    return reqs


def run_engine_load(engine, workload: List[dict],
                    timeout_s: float = 300.0) -> Dict:
    """Replay a schedule straight against one :class:`LLMEngine`
    (no serve layer — this leg measures engine decode work, not
    routing). One consumer thread per request, schedule-paced."""
    results: List[dict] = []
    errors: List[str] = []
    lock = threading.Lock()
    t0 = time.monotonic()

    def consume(r):
        delay = r["arrival_s"] - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        try:
            toks = list(engine.generate_sync(
                r["prompt"], r["max_new_tokens"], timeout_s=timeout_s))
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(f"{type(e).__name__}: {e}")
            return
        with lock:
            results.append({"tokens": len(toks), "long": r.get("long")})

    threads = [threading.Thread(target=consume, args=(r,))
               for r in workload]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=timeout_s)
    wall = max(time.monotonic() - t0, 1e-9)
    return {"tokens_total": sum(r["tokens"] for r in results),
            "wall_s": round(wall, 3),
            "requests_done": len(results),
            "errors": errors}


def bench_mixed_lengths(model: Dict, engine: Dict, seed: int,
                        requests: int = 24, clients: int = 8) -> Dict:
    """The length-aware serving claim, measured on a live engine: a
    mixed short+long workload's decode steps touch
    ``decode_pages_live`` pages out of the ``decode_pages_window`` the
    gather reference pays — ``work_reduction = 1 − live/window`` is the
    FLOP fraction the Pallas kernel's block skipping removes (wall
    clock follows on TPU where the kernel dispatches; the accounting
    is backend-independent). Also reports the engine's device-wall
    split (prefill vs decode) per step."""
    from ray_tpu.models import TransformerConfig
    from ray_tpu.serve.llm_engine import EngineConfig, LLMEngine

    mconf = {k: v for k, v in model.items()}
    if "dtype" in mconf:
        from ray_tpu.serve.llm_engine import _resolve_dtype
        mconf["dtype"] = _resolve_dtype(mconf["dtype"])
    eng = LLMEngine(TransformerConfig(**mconf), EngineConfig(**engine),
                    seed=seed)
    try:
        # warm the jitted programs outside the window
        list(eng.generate_sync([3, 5, 7], 2))
        workload = make_mixed_workload(requests, clients, seed, engine)
        load = run_engine_load(eng, workload)
        s = eng.stats()
    finally:
        eng.shutdown()
    frac = s.get("decode_block_work_frac")
    steps = max(s.get("decode_steps") or 0, 1)
    return {
        "requests": requests,
        "tokens_total": load["tokens_total"],
        "wall_s": load["wall_s"],
        "errors": load["errors"],
        "decode_steps": s.get("decode_steps"),
        "decode_pages_live": s.get("decode_pages_live"),
        "decode_pages_window": s.get("decode_pages_window"),
        "decode_block_work_frac": frac,
        "work_reduction": (round(1.0 - frac, 4)
                           if frac is not None else None),
        "decode_wall_s": s.get("decode_wall_s"),
        "prefill_wall_s": s.get("prefill_wall_s"),
        "decode_step_ms": round(
            1e3 * (s.get("decode_wall_s") or 0.0) / steps, 3),
    }


def bench_trace_overhead(model: Dict, engine: Dict, seed: int,
                         requests: int = 16, clients: int = 4) -> Dict:
    """Per-request tracing overhead guard: the SAME seeded schedule
    replays against one engine with request tracing forced ON (every
    request records spans; tail sampling still decides shipping) and
    one with it OFF — tokens/s with tracing on must stay within 2% of
    off for the SERVE gate's claim that observability rides free. Also
    microbenches the span-record hot path itself (one dict build + one
    append at the per-request cap, the worst case) against its <=20µs
    bound. Wall-clock ratios on a noisy shared CPU are recorded, not
    hard-failed; the span bound is deterministic enough to gate."""
    from ray_tpu.models import TransformerConfig
    from ray_tpu.serve.llm_engine import (EngineConfig, LLMEngine,
                                          _resolve_dtype)
    from ray_tpu.serve.request_trace import RequestTrace

    mconf = dict(model)
    if "dtype" in mconf:
        mconf["dtype"] = _resolve_dtype(mconf["dtype"])
    workload = make_workload(requests, clients, seed,
                             mean_interarrival_s=0.002,
                             prompt_rng=(4, 12), out_rng=(8, 16))
    runs: Dict[str, Dict] = {}
    for label, on in (("on", True), ("off", False)):
        eng = LLMEngine(TransformerConfig(**mconf),
                        EngineConfig(**dict(engine, enable_trace=on)),
                        seed=seed)
        try:
            list(eng.generate_sync([3, 5, 7], 2))   # warm the jits
            # best of two replays: at these wall times thread-spawn
            # jitter rivals the effect being measured
            load = min((run_engine_load(eng, workload)
                        for _ in range(2)),
                       key=lambda r: r["wall_s"])
        finally:
            eng.shutdown()
        runs[label] = {
            "tokens_total": load["tokens_total"],
            "wall_s": load["wall_s"],
            "tokens_per_s": round(
                load["tokens_total"] / max(load["wall_s"], 1e-9), 2),
            "errors": load["errors"],
        }
    on_tps = runs["on"]["tokens_per_s"]
    off_tps = runs["off"]["tokens_per_s"]
    # span-record microbench at the per-request cap (drop-oldest is the
    # steady state of a long decode — the worst case of the hot path)
    tr = RequestTrace("req-bench-span")
    iters = 20_000
    t0 = time.perf_counter()
    for _ in range(iters):
        tr.span("DECODE", 1.0, 2.0, tokens=16)
    span_us = (time.perf_counter() - t0) / iters * 1e6
    return {
        "requests": requests,
        "tracing_on": runs["on"],
        "tracing_off": runs["off"],
        "overhead_pct": (round(100.0 * (off_tps - on_tps) / off_tps, 2)
                         if off_tps else None),
        "within_2pct": (off_tps > 0
                        and on_tps >= 0.98 * off_tps),
        "span_record_us": round(span_us, 3),
        "span_budget_us": 20.0,
    }


def bench_paged_kernel(on_tpu: bool, seed: int = 0) -> Dict:
    """Kernel-vs-reference leg at the op level: one mixed-length paged
    batch (half the sequences near-empty, half filling the window).
    Everywhere: exact-parity check (fp32-softmax tolerance) and the
    page-count work reduction the lens skipping buys. On TPU: compiled
    wall-clock of kernel vs gather reference (the dispatch the engine
    takes); on CPU the kernel runs in interpret mode, so wall times are
    reported for the reference only and the FLOP proportionality
    stands in as the gain metric."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from ray_tpu.ops import paged_attention, paged_work_pages

    B, H, KVH = 8, 8, 2
    D = 128 if on_tpu else 32
    bs, T = (32, 32) if on_tpu else (16, 8)
    rng = np.random.default_rng(seed)
    N = 1 + B * T
    dt = np.float32
    kc = rng.normal(size=(N, bs, KVH, D)).astype(dt)
    vc = rng.normal(size=(N, bs, KVH, D)).astype(dt)
    q = rng.normal(size=(B, 1, H, D)).astype(dt)
    bt = rng.permutation(np.arange(1, N)).astype(np.int32).reshape(B, T)
    # mixed lengths: even slots hold a handful of tokens, odd slots a
    # full window — the serving slot array under ragged traffic
    lens = np.asarray([bs + 3 if i % 2 == 0 else T * bs
                       for i in range(B)], np.int32)
    pos = (lens - 1)[:, None].astype(np.int32)

    ref_fn = jax.jit(lambda *a: paged_attention(*a, impl="reference"))
    ker_fn = jax.jit(lambda q_, k_, v_, bt_, p_, l_: paged_attention(
        q_, k_, v_, bt_, p_, lens=l_, impl="kernel"))
    ref = np.asarray(ref_fn(q, kc, vc, bt, pos))
    ker = np.asarray(ker_fn(q, kc, vc, bt, pos, lens))
    parity = float(np.max(np.abs(ref - ker)))

    pages_live = int(np.sum(paged_work_pages(lens, bs)))
    pages_window = B * T
    out = {
        "batch": B, "block_size": bs, "table_len": T,
        "heads": H, "kv_heads": KVH, "head_dim": D,
        "lens": lens.tolist(),
        "parity_max_abs": round(parity, 8),
        "pages_live": pages_live,
        "pages_window": pages_window,
        "work_reduction": round(1.0 - pages_live / pages_window, 4),
        "kernel_mode": "compiled" if on_tpu else "interpret",
    }

    def _time(fn, args, iters=20):
        r = fn(*args)
        jax.block_until_ready(r)
        t0 = time.monotonic()
        for _ in range(iters):
            r = fn(*args)
        jax.block_until_ready(r)
        return (time.monotonic() - t0) / iters

    wall_ref = _time(ref_fn, (q, kc, vc, bt, pos))
    out["wall_ref_ms"] = round(wall_ref * 1e3, 4)
    if on_tpu:
        # interpret-mode wall is interpreter overhead, not kernel cost:
        # only the compiled TPU kernel is timed against the reference
        wall_ker = _time(ker_fn, (q, kc, vc, bt, pos, lens))
        out["wall_kernel_ms"] = round(wall_ker * 1e3, 4)
        out["kernel_speedup"] = round(wall_ref / wall_ker, 3) \
            if wall_ker else None
    return out


# ------------------------------------------------- disaggregated legs
def _disagg_fleet_run(name: str, model: Dict, engine: Dict,
                      workload: List[dict], clients: int,
                      decode_slots: int,
                      timeout_s: float = 600.0) -> Dict:
    """One disaggregated measurement: 1 prefill + 1 decode replica
    (2 procs), KV shipped between them, the decode replica running
    ``decode_slots`` slots since it never interleaves prefill chunks.
    Returns the load plus the hand-off accounting from both fleets."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api
    from ray_tpu.serve.disagg import deploy_disaggregated

    router = deploy_disaggregated(
        model, engine, name=name, num_prefill=1, num_decode=1,
        decode_slots=decode_slots,
        max_ongoing_requests=4 * clients + 8)
    # one throwaway request compiles both fleets' programs (and the
    # hand-off path) outside the measured window
    list(router.options(stream=True).generate.remote(
        workload[0]["prompt"][:4], 2))
    load = run_load(lambda: router, workload, clients,
                    timeout_s=timeout_s)
    ctrl = serve_api._controller_or_none()
    pf = ray_tpu.get(ctrl.get_replicas.remote(f"{name}-prefill"))
    dc = ray_tpu.get(ctrl.get_replicas.remote(f"{name}-decode"))
    pstats = [(ray_tpu.get(r.stats.remote(), timeout=60) or {}
               ).get("engine") or {} for r in pf]
    dstats = [(ray_tpu.get(r.stats.remote(), timeout=60) or {}
               ).get("engine") or {} for r in dc]
    audits = [ray_tpu.get(r.handle_request.remote("pool_audit"),
                          timeout=60) for r in pf + dc]
    serve.delete(f"{name}-prefill")
    serve.delete(f"{name}-decode")
    adopts = sum(e.get("kv_adopts") or 0 for e in dstats)
    ship_bytes = sum(e.get("kv_adopt_bytes") or 0 for e in dstats)
    ship_wall = sum(e.get("kv_ship_wall_s") or 0.0 for e in dstats)
    occ = {}
    for e in dstats:
        for k, v in (e.get("occupancy_hist") or {}).items():
            occ[int(k)] = occ.get(int(k), 0) + v
    steps = sum(occ.values())
    mean_occ = (sum(k * v for k, v in occ.items()) / steps
                if steps else 0.0)
    return {
        "replicas": 2,
        "decode_slots": decode_slots,
        "tokens_per_s": load["tokens_per_s"],
        "tokens_per_s_chip": round(load["tokens_per_s"] / 2, 2),
        "ttft_ms": load["ttft_ms"],
        "inter_token_ms": load["inter_token_ms"],
        "wall_s": load["wall_s"],
        "tokens_total": load["tokens_total"],
        "requests_done": load["requests_done"],
        "errors": load["errors"],
        "router": dict(router.stats),
        "kv_adopts": adopts,
        "kv_ship_bytes_total": ship_bytes,
        "kv_ship_wall_s": round(ship_wall, 4),
        "kv_ship_bytes_per_request": (round(ship_bytes / adopts)
                                      if adopts else None),
        "kv_ship_ms_per_request": (round(1e3 * ship_wall / adopts, 3)
                                   if adopts else None),
        "kv_exports": sum(e.get("kv_exports") or 0 for e in pstats),
        "decode_slot_occupancy": round(mean_occ / decode_slots, 4)
        if decode_slots else None,
        "pool_audits_clean": all(a == [] for a in audits),
    }


def bench_disagg(model: Dict, engine: Dict, seed: int, clients: int,
                 requests: int, mean_interarrival_s: float,
                 prompt_rng, out_rng, timeout_s: float = 600.0) -> Dict:
    """The disaggregation comparison at equal chip count: 1 prefill +
    1 decode replica (decode running 2x the slots — it never
    interleaves prefill) vs 2 colocated replicas, same seeded Poisson
    schedule of long-prefill/short-decode requests. Long prompts make
    colocated replicas stall decode behind chunk trains; the decode
    fleet never does, which is the tokens/s/chip claim. Also reports
    the hand-off's measured cost: KV bytes + wall per shipped
    request."""
    workload = make_workload(requests, clients, seed,
                             mean_interarrival_s=mean_interarrival_s,
                             prompt_rng=prompt_rng, out_rng=out_rng)
    coloc = _fleet_leg("llm_disagg_base", model, engine, workload,
                       clients, replicas=2, policy="gauge",
                       timeout_s=timeout_s)
    disagg = _disagg_fleet_run(
        "llm_disagg", model, engine, workload, clients,
        decode_slots=2 * engine["decode_slots"], timeout_s=timeout_s)
    disagg["clients"] = clients
    disagg["requests"] = requests
    disagg["kv_wire"] = engine.get("kv_wire", "bf16")
    disagg["colocated"] = coloc
    disagg["vs_colocated"] = (
        round(disagg["tokens_per_s_chip"] / coloc["tokens_per_s_chip"],
              3) if coloc["tokens_per_s_chip"] else None)
    return disagg


def bench_disagg_parity(model: Dict, engine: Dict, seed: int) -> Dict:
    """Greedy bit-parity, disagg on vs off: the same prompt decoded
    colocated and via prefill_export -> ship -> submit_adopt on a
    SECOND engine (same seed => identical params) must produce
    bit-identical token streams on the exact "bf16" wire; the int8
    wire must stay within quantization tolerance (identical tokens are
    typical but not guaranteed, so only exactness of the default wire
    gates)."""
    from ray_tpu.models import TransformerConfig
    from ray_tpu.serve.llm_engine import (EngineConfig, LLMEngine,
                                          _resolve_dtype)

    mconf = dict(model)
    if "dtype" in mconf:
        mconf["dtype"] = _resolve_dtype(mconf["dtype"])
    rng = random.Random(seed + 7)
    prompt = [rng.randrange(2, 128)
              for _ in range(3 * engine["kv_block_size"] + 3)]
    out: Dict[str, Dict] = {}
    for wire in ("bf16", "int8"):
        a = LLMEngine(TransformerConfig(**mconf),
                      EngineConfig(**dict(engine, kv_wire=wire)),
                      seed=seed)
        b = LLMEngine(TransformerConfig(**mconf),
                      EngineConfig(**dict(engine, kv_wire=wire)),
                      seed=seed)
        try:
            ref = list(a.generate_sync(prompt, 16))
            payload = a.prefill_export(prompt)
            req = b.submit_adopt(payload, max_new_tokens=16)
            got = _drain_request(b, req)
            out[wire] = {
                "bit_identical": ref == got,
                "tokens": len(got),
                "wire_bytes": payload["wire_bytes"],
            }
        finally:
            a.shutdown()
            b.shutdown()
    out["ok"] = bool(out["bf16"]["bit_identical"])
    return out


def _drain_request(engine, req) -> List[int]:
    from ray_tpu.serve.llm_engine import _DONE
    toks: List[int] = []
    try:
        while True:
            item = req.out.get(timeout=60)
            if item is _DONE:
                return toks
            if isinstance(item, BaseException):
                raise item
            toks.append(item)
    finally:
        engine.cancel(req)


def bench_migration(model: Dict, engine: Dict, seed: int,
                    sessions: int = 4, turns: int = 3) -> Dict:
    """Warm-prefix migration across a drain, A/B: a victim engine is
    warmed with ``sessions`` distinct shared prefixes (``turns``
    requests each, so the trie chains carry hits), then its warm
    chains are exported and imported into survivor A; survivor B
    starts cold (the no-migration drain). The SAME single-pass replay
    (one request per session) runs on each: A's prefix hit rate must
    strictly beat B's, which only scores within-replay repeats (none
    here)."""
    from ray_tpu.models import TransformerConfig
    from ray_tpu.serve.llm_engine import (EngineConfig, LLMEngine,
                                          _resolve_dtype)

    mconf = dict(model)
    if "dtype" in mconf:
        mconf["dtype"] = _resolve_dtype(mconf["dtype"])
    bs = engine["kv_block_size"]
    rng = random.Random(seed + 13)
    prefixes = [[rng.randrange(2, 128) for _ in range(3 * bs)]
                for _ in range(sessions)]

    def make(tag):
        return LLMEngine(TransformerConfig(**mconf),
                         EngineConfig(**engine), seed=seed,
                         replica_tag=tag)

    victim = make("victim")
    surv_a = make("survivor_migrated")
    surv_b = make("survivor_cold")
    try:
        for p in prefixes:
            for t in range(turns):
                list(victim.generate_sync(p + [40 + t], 4))
        payload = victim.export_warm_prefixes(min_hits=1)
        migrated = surv_a.import_warm_prefixes(payload) \
            if payload is not None else 0

        def replay(eng):
            for i, p in enumerate(prefixes):
                list(eng.generate_sync(p + [99, i], 4))
            s = eng.stats()
            return {
                "prefix_hit_blocks": s["prefix_hit_blocks_total"],
                "prompt_blocks": s["prompt_blocks_total"],
                "prefix_hit_rate": s["prefix_hit_rate"] or 0.0,
            }

        with_mig = replay(surv_a)
        without = replay(surv_b)
        audits = [victim.pool_audit(), surv_a.pool_audit(),
                  surv_b.pool_audit()]
    finally:
        victim.shutdown()
        surv_a.shutdown()
        surv_b.shutdown()
    return {
        "sessions": sessions,
        "turns": turns,
        "migrated_blocks": migrated,
        "payload_bytes": (payload or {}).get("wire_bytes"),
        "with_migration": with_mig,
        "without_migration": without,
        "hit_retention": round(
            with_mig["prefix_hit_rate"]
            - without["prefix_hit_rate"], 4),
        "migration_wins": with_mig["prefix_hit_rate"]
        > without["prefix_hit_rate"],
        "pool_audits_clean": all(a == [] for a in audits),
    }


def _scale_up_run(name: str, model: Dict, engine: Dict,
                  workload: List[dict], clients: int,
                  autoscale: bool, timeout_s: float):
    """One measurement of the scale-up comparison: deploy (with or
    without the gauge-driven autoscaler), replay the schedule, and
    return (load, per-replica token counts)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    kw: Dict = {"max_ongoing_requests": 4 * clients + 8}
    if autoscale:
        kw["autoscaling_config"] = {
            "min_replicas": 1, "max_replicas": 2,
            # classic ongoing-request pressure is hidden by continuous
            # batching; scale on the ENGINE backlog instead
            "target_ongoing_requests": 1e9,
            "target_queue_depth": 1.0,
            "upscale_delay_s": 0.5,
            "downscale_delay_s": 3600.0,
        }
    else:
        kw["num_replicas"] = 1
    dep = serve.deployment(name=name, **kw)(serve.LLMServer)
    serve.run(dep.bind(model=model, engine=engine), name=name)
    handle = serve.get_app_handle(name)
    list(handle.options(stream=True).generate.remote([2, 3, 5], 2))
    load = run_load(lambda: serve.get_app_handle(name), workload,
                    clients, timeout_s=timeout_s,
                    handle_opts={"routing_policy": "gauge"})
    ctrl = serve_api._controller_or_none()
    reps = ray_tpu.get(ctrl.get_replicas.remote(name))
    stats = [ray_tpu.get(r.stats.remote(), timeout=60) for r in reps]
    per_replica = [(s.get("engine") or {}).get("tokens_total") or 0
                   for s in stats]
    serve.delete(name)
    return load, per_replica


def _scale_up_leg(model: Dict, engine: Dict, seed: int, clients: int,
                  requests: int, mean_interarrival_s: float,
                  timeout_s: float = 300.0) -> Dict:
    """Autoscaling fleet under load: a deliberately backlogged single
    replica must scale up MID-RUN off its engine gauges, the gauge
    router must start sending traffic to the new replica, and tail
    TTFT must recover. Recovery is measured against the SAME seeded
    schedule on a pinned 1-replica fleet: ``ttft_recovery`` =
    late-half p99 TTFT without the autoscaler / with it (> 1 means
    the added replica absorbed the backlog)."""
    # sustained marginal overload, not a burst: arrivals spread across
    # the whole leg so the single-replica baseline's queue KEEPS
    # growing while the autoscaled fleet's second replica (joining
    # warm — LLMServer compiles in __init__) absorbs the tail
    workload = make_workload(requests, clients, seed,
                             mean_interarrival_s=mean_interarrival_s,
                             prompt_rng=(4, 12), out_rng=(32, 48))

    def late_p99(load) -> Optional[float]:
        ttfts = [t for _, t in load.get("ttft_series") or []]
        return _percentile(ttfts[len(ttfts) // 2:], 99)

    auto, per_replica = _scale_up_run(
        "llm_scaleup", model, engine, workload, clients,
        autoscale=True, timeout_s=timeout_s)
    base, _ = _scale_up_run(
        "llm_scaleup_base", model, engine, workload, clients,
        autoscale=False, timeout_s=timeout_s)
    late_auto, late_base = late_p99(auto), late_p99(base)
    total = sum(per_replica) or 1
    new_tokens = min(per_replica) if len(per_replica) > 1 else 0
    return {
        "requests": requests,
        "clients": clients,
        "replicas_end": len(per_replica),
        "per_replica_tokens": per_replica,
        "new_replica_tokens": new_tokens,
        # fraction of fleet tokens the mid-run replica served — the
        # machine-independent proof that routing reached it (wall-clock
        # recovery needs one chip per replica; on a shared CPU core a
        # second replica only time-slices, so ttft_recovery < 1 there)
        "new_replica_share": round(new_tokens / total, 4),
        "scaled_up": len(per_replica) > 1,
        "tokens_per_s": auto["tokens_per_s"],
        "ttft_ms": auto["ttft_ms"],
        "ttft_p99_late_ms": _ms(late_auto),
        "baseline_tokens_per_s": base["tokens_per_s"],
        "baseline_ttft_ms": base["ttft_ms"],
        "baseline_ttft_p99_late_ms": _ms(late_base),
        "ttft_recovery": (round(late_base / late_auto, 3)
                          if late_base and late_auto else None),
        "errors": auto["errors"] + base["errors"],
        "wall_s": auto["wall_s"],
    }


def _fleet_leg(name: str, model: Dict, engine: Dict, workload: List[dict],
               clients: int, replicas: int, policy: str,
               timeout_s: float = 600.0) -> Dict:
    """One fleet measurement: deploy ``replicas`` copies, warm every
    replica's jitted programs round-robin outside the window, replay
    the schedule with ``policy`` routing, and fold in the per-replica
    engine counters (prefix hits, speculation acceptance)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    dep = serve.deployment(
        name=name, num_replicas=replicas,
        max_ongoing_requests=4 * clients + 8)(serve.LLMServer)
    serve.run(dep.bind(model=model, engine=engine), name=name)
    handle = serve.get_app_handle(name)
    for _ in range(replicas):
        list(handle.options(
            stream=True, routing_policy="round_robin").generate.remote(
                workload[0]["prompt"][:4], 2))
    load = run_load(lambda: serve.get_app_handle(name), workload,
                    clients, timeout_s=timeout_s,
                    handle_opts={"routing_policy": policy})
    ctrl = serve_api._controller_or_none()
    reps = ray_tpu.get(ctrl.get_replicas.remote(name))
    stats = [ray_tpu.get(r.stats.remote(), timeout=60) for r in reps]
    engines = [s.get("engine") or {} for s in stats]
    hit = sum(e.get("prefix_hit_blocks_total") or 0 for e in engines)
    pblocks = sum(e.get("prompt_blocks_total") or 0 for e in engines)
    drafted = sum((e.get("spec") or {}).get("drafted") or 0
                  for e in engines)
    accepted = sum((e.get("spec") or {}).get("accepted") or 0
                   for e in engines)
    serve.delete(name)
    return {
        "replicas": replicas,
        "routing": policy,
        "tokens_per_s": load["tokens_per_s"],
        "tokens_per_s_chip": round(load["tokens_per_s"] / replicas, 2),
        "ttft_ms": load["ttft_ms"],
        "inter_token_ms": load["inter_token_ms"],
        "wall_s": load["wall_s"],
        "tokens_total": load["tokens_total"],
        "requests_done": load["requests_done"],
        "errors": load["errors"],
        "prefix_hit_blocks": hit,
        "prompt_blocks": pblocks,
        "prefix_hit_rate": round(hit / pblocks, 4) if pblocks else None,
        "spec_drafted": drafted,
        "spec_accepted": accepted,
        "spec_acceptance": (round(accepted / drafted, 4)
                            if drafted else None),
        "per_replica_tokens": [e.get("tokens_total") for e in engines],
    }


def bench_fleet(model: Dict, engine: Dict, replicas: int, clients: int,
                requests: int, seed: int, sys_prompt_tokens: int,
                prompt_rng, out_rng, mean_interarrival_s: float,
                timeout_s: float = 600.0) -> Dict:
    """The fleet comparison: prefix sharing + prompt-lookup speculation
    + gauge routing vs the sharing-off / speculation-off / round-robin
    baseline on the SAME seeded schedule. Every prompt opens with one
    common system prompt ``sys_prompt_tokens`` long (>= 4 KV blocks)."""
    rng = random.Random(seed + 1)
    system_prompt = [rng.randrange(2, 128)
                     for _ in range(sys_prompt_tokens)]
    workload = make_workload(requests, clients, seed,
                             mean_interarrival_s=mean_interarrival_s,
                             prompt_rng=prompt_rng, out_rng=out_rng,
                             system_prompt=system_prompt)
    eng_on = dict(engine, enable_prefix_sharing=True, spec_tokens=4)
    eng_off = dict(engine, enable_prefix_sharing=False, spec_tokens=0)
    fleet = _fleet_leg("llm_fleet", model, eng_on, workload, clients,
                       replicas, policy="gauge", timeout_s=timeout_s)
    base = _fleet_leg("llm_fleet_base", model, eng_off, workload,
                      clients, replicas, policy="round_robin",
                      timeout_s=timeout_s)
    fleet["system_prompt_tokens"] = sys_prompt_tokens
    fleet["clients"] = clients
    fleet["requests"] = requests
    fleet["baseline"] = base
    fleet["vs_baseline"] = (
        round(fleet["tokens_per_s_chip"] / base["tokens_per_s_chip"], 2)
        if base["tokens_per_s_chip"] else None)
    return fleet


def bench(smoke: bool = False, clients: int = 8, requests: int = 24,
          seed: int = 0, fleet_replicas: int = 0,
          fleet_clients: int = 0, fleet_requests: int = 0,
          scale_up: bool = True) -> dict:
    import jax

    import ray_tpu
    from ray_tpu import serve

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    if smoke:
        clients, requests = min(clients, 4), min(requests, 6)
        model = {"vocab_size": 128, "d_model": 32, "n_layers": 2,
                 "n_heads": 4, "head_dim": 8, "d_ff": 64,
                 "max_seq_len": 128, "rotary_dim": 8, "dtype": "float32",
                 "remat_policy": "none"}
        engine = {"decode_slots": clients, "kv_block_size": 8,
                  "max_seq_len": 64, "prefill_chunk": 16}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.02,
                                 prompt_rng=(4, 12), out_rng=(6, 10))
        fleet_kw = dict(replicas=fleet_replicas or 2,
                        clients=fleet_clients or 6,
                        requests=fleet_requests or 12,
                        sys_prompt_tokens=4 * engine["kv_block_size"],
                        prompt_rng=(2, 6), out_rng=(6, 10),
                        mean_interarrival_s=0.02, timeout_s=120.0)
        mixed_kw = dict(requests=10, clients=4)
        trace_kw = dict(requests=8, clients=4)
        scale_kw = dict(clients=8, requests=40,
                        mean_interarrival_s=0.06, timeout_s=150.0)
        disagg_kw = dict(clients=4, requests=8,
                         mean_interarrival_s=0.02,
                         prompt_rng=(16, 40), out_rng=(4, 8),
                         timeout_s=120.0)
    elif on_tpu:
        model = {"vocab_size": 32000, "d_model": 2048, "n_layers": 8,
                 "n_heads": 16, "head_dim": 128, "d_ff": 8192,
                 "max_seq_len": 2048, "rotary_dim": 64,
                 "dtype": "bfloat16", "remat_policy": "none"}
        engine = {"decode_slots": 32, "kv_block_size": 32,
                  "max_seq_len": 1024, "prefill_chunk": 256}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.05,
                                 prompt_rng=(32, 512), out_rng=(32, 128))
        fleet_kw = dict(replicas=fleet_replicas or 4,
                        clients=fleet_clients or 200,
                        requests=fleet_requests or 400,
                        sys_prompt_tokens=4 * engine["kv_block_size"],
                        prompt_rng=(16, 128), out_rng=(32, 128),
                        mean_interarrival_s=0.02)
        mixed_kw = dict(requests=64, clients=32)
        trace_kw = dict(requests=48, clients=16)
        scale_kw = dict(clients=64, requests=128,
                        mean_interarrival_s=0.005)
        disagg_kw = dict(clients=64, requests=128,
                         mean_interarrival_s=0.01,
                         prompt_rng=(256, 768), out_rng=(16, 64))
    else:
        # CPU sizing: wide enough that a decode step is weight-stream /
        # gemv bound, so step cost is nearly batch-independent — the
        # same regime a real chip is in at decode batch 1 (MXU idle),
        # which is what continuous batching amortizes. Arrivals are
        # compressed so the queue saturates the slots (the serial
        # baseline queues identically).
        model = {"vocab_size": 1024, "d_model": 256, "n_layers": 2,
                 "n_heads": 4, "head_dim": 32, "d_ff": 1024,
                 "max_seq_len": 256, "rotary_dim": 16,
                 "dtype": "float32", "remat_policy": "none"}
        engine = {"decode_slots": clients, "kv_block_size": 16,
                  "max_seq_len": 128, "prefill_chunk": 32}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.005,
                                 prompt_rng=(8, 24), out_rng=(24, 48))
        fleet_kw = dict(replicas=fleet_replicas or 2,
                        clients=fleet_clients or 32,
                        requests=fleet_requests or 64,
                        sys_prompt_tokens=4 * engine["kv_block_size"],
                        prompt_rng=(4, 16), out_rng=(16, 32),
                        mean_interarrival_s=0.01)
        mixed_kw = dict(requests=24, clients=8)
        trace_kw = dict(requests=16, clients=4)
        scale_kw = dict(clients=12, requests=100,
                        mean_interarrival_s=0.06)
        # long-prefill/short-decode shape: prompts span 2-3 prefill
        # chunks while outputs stay short of the prompt, so colocated
        # replicas interleave chunk trains with half-batch decode — the
        # regime disaggregation targets (the decode fleet runs 2x slots
        # at weight-stream-bound step cost, halving decode steps)
        disagg_kw = dict(clients=8, requests=32,
                         mean_interarrival_s=0.02,
                         prompt_rng=(48, 96), out_rng=(16, 32))

    # clusterless legs first: the paged-kernel op comparison and the
    # mixed-length engine run need a device, not the cluster
    paged = bench_paged_kernel(on_tpu, seed=seed)
    mixed = bench_mixed_lengths(model, engine, seed=seed, **mixed_kw)
    trace = bench_trace_overhead(model, engine, seed=seed, **trace_kw)
    parity = bench_disagg_parity(model, engine, seed=seed)
    migration = bench_migration(model, engine, seed=seed)

    ray_tpu.init(num_cpus=max(8, clients + 4,
                              fleet_kw["clients"] // 2 + 6),
                 _num_initial_workers=3, ignore_reinit_error=True)
    modes = {}
    stats = {}
    try:
        for mode, slots in (("continuous", engine["decode_slots"]),
                            ("serial", 1)):
            ecfg = dict(engine, decode_slots=slots)
            name = f"llm_{mode}"
            dep = serve.deployment(
                name=name, max_ongoing_requests=4 * clients + 8)(
                    serve.LLMServer)
            serve.run(dep.bind(model=model, engine=ecfg), name=name)
            handle = serve.get_app_handle(name)
            # one throwaway request compiles prefill+decode outside the
            # measured window (admission itself never recompiles)
            list(handle.options(stream=True).generate.remote(
                workload[0]["prompt"][:4], 2))
            modes[mode] = run_load(
                lambda name=name: serve.get_app_handle(name),
                workload, clients)
            stats[mode] = handle.stats.remote().result(timeout_s=60)
            serve.delete(name)
        # fleet leg: shared system prompt, gauge routing, prefix
        # sharing + speculation vs the round-robin no-sharing baseline
        t_fleet = time.monotonic()
        fleet = bench_fleet(model, engine, seed=seed, **fleet_kw)
        fleet["leg_wall_s"] = round(time.monotonic() - t_fleet, 2)
        # disaggregated prefill/decode vs colocated at equal chip count,
        # same seeded long-prefill/short-decode schedule
        t_disagg = time.monotonic()
        disagg = bench_disagg(model, engine, seed=seed, **disagg_kw)
        disagg["leg_wall_s"] = round(time.monotonic() - t_disagg, 2)
        # autoscaling fleet under load: a backlogged single replica
        # must scale up MID-RUN and TTFT must recover (--scale-up-mid-
        # load; a deliberately small engine so the backlog forms fast)
        scale = None
        if scale_up:
            t_scale = time.monotonic()
            scale = _scale_up_leg(
                model, dict(engine, decode_slots=1), seed=seed,
                **scale_kw)
            scale["leg_wall_s"] = round(time.monotonic() - t_scale, 2)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()

    cont, ser = modes["continuous"], modes["serial"]
    n_chips = 1   # the engine decodes on one device
    vs_serial = (round(cont["tokens_per_s"] / ser["tokens_per_s"], 2)
                 if ser["tokens_per_s"] else None)
    return {
        "metric": "serve_tokens_per_s_chip",
        "value": round(cont["tokens_per_s"] / n_chips, 2),
        "unit": "tokens/s/chip",
        "vs_serial": vs_serial,
        "detail": {
            "backend": backend,
            "n_chips": n_chips,
            # record the host's core count: CPU-backend ratios (e.g.
            # vs_serial) compress when every replica time-slices one
            # core, and the baseline locks read this to judge them
            "host_cpus": os.cpu_count(),
            "clients": clients,
            "requests": requests,
            "seed": seed,
            "model": model,
            "engine": engine,
            "continuous": cont,
            "serial": ser,
            "occupancy_hist": stats["continuous"].get("occupancy_hist"),
            "engine_stats": {m: {k: s.get(k) for k in
                                 ("tokens_total", "decode_steps",
                                  "prefill_chunks", "free_blocks",
                                  "total_blocks")}
                             for m, s in stats.items()},
            "fleet": fleet,
            "disagg": disagg,
            "disagg_parity": parity,
            "migration": migration,
            "paged_kernel": paged,
            "mixed_len": mixed,
            "trace_overhead": trace,
            "scale_up": scale,
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny workload for CI (subprocess smoke test)")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fleet-replicas", type=int, default=0,
                    help="fleet-leg replica count (0 = per-backend "
                         "default: 2 CPU / 4 TPU)")
    ap.add_argument("--fleet-clients", type=int, default=0,
                    help="fleet-leg Poisson clients (0 = default)")
    ap.add_argument("--fleet-requests", type=int, default=0,
                    help="fleet-leg request count (0 = default)")
    ap.add_argument("--scale-up-mid-load",
                    action=argparse.BooleanOptionalAction, default=True,
                    help="run the autoscaling-fleet-under-load leg "
                         "(one backlogged replica must scale up "
                         "mid-run; --no-scale-up-mid-load skips it)")
    args = ap.parse_args()
    rec = bench(smoke=args.smoke, clients=args.clients,
                requests=args.requests, seed=args.seed,
                fleet_replicas=args.fleet_replicas,
                fleet_clients=args.fleet_clients,
                fleet_requests=args.fleet_requests,
                scale_up=args.scale_up_mid_load)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
