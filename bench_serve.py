#!/usr/bin/env python
"""Serving benchmark: continuous-batching tokens/s/chip under a
synthetic many-client load (the SERVE metric, gated by
``tools/perf_gate.py --metric serve``).

Prints ONE JSON line:
``{"metric": "serve_tokens_per_s_chip", "value", "unit", "vs_serial",
"detail"}``.

Workload: ``--clients`` concurrent clients replay a seeded schedule of
``--requests`` requests with Poisson arrivals and sampled prompt/output
lengths against a Serve deployment of :class:`ray_tpu.serve.LLMServer`,
each consuming its token stream through
``handle.options(stream=True)`` — the full engine + streaming +
reliable-delivery path, not a model-only microbench. The same schedule
then replays against a ``decode_slots=1`` engine (serial per-request
decode, everything else identical): ``vs_serial`` is the
continuous-batching speedup, the headline claim of the engine.

Reported: tokens/s/chip (headline), TTFT p50/p99, inter-token latency
p50/p99, the engine's batch-occupancy histogram, and the engine/model
config that produced them. ``--smoke`` shrinks everything for CI.

On TPU the model is sized up with the chip; on CPU a tiny config keeps
the harness runnable anywhere (the CPU record is a smoke point for the
serve series, like the CPU BENCH records).
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
from typing import Dict, List, Optional


def _percentile(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    i = min(int(p / 100.0 * len(xs)), len(xs) - 1)
    return xs[i]


def make_workload(n_requests: int, clients: int, seed: int,
                  mean_interarrival_s: float,
                  prompt_rng=(4, 48), out_rng=(8, 32)) -> List[dict]:
    """Seeded request schedule: Poisson arrivals (exponential
    inter-arrival gaps), uniform prompt/output lengths. The SAME
    schedule replays against both engine modes."""
    rng = random.Random(seed)
    t = 0.0
    reqs = []
    for i in range(n_requests):
        t += rng.expovariate(1.0 / mean_interarrival_s)
        plen = rng.randint(*prompt_rng)
        reqs.append({
            "arrival_s": t,
            "prompt": [rng.randrange(2, 128) for _ in range(plen)],
            "max_new_tokens": rng.randint(*out_rng),
            "client": i % clients,
        })
    return reqs


def run_load(handle_factory, workload: List[dict], clients: int,
             timeout_s: float = 600.0) -> Dict:
    """Replay the schedule with one thread + one handle per client;
    per-request TTFT / inter-token gaps are recorded client-side (what
    a user of the HTTP proxy would observe)."""
    per_client: Dict[int, List[dict]] = {c: [] for c in range(clients)}
    for r in workload:
        per_client[r["client"]].append(r)
    results: List[dict] = []
    errors: List[str] = []
    lock = threading.Lock()
    t0 = time.monotonic()

    def client_loop(cid: int):
        handle = handle_factory()
        for r in per_client[cid]:
            delay = r["arrival_s"] - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            rec = {"client": cid, "tokens": 0}
            t_submit = time.monotonic()
            try:
                gen = handle.options(stream=True).generate.remote(
                    r["prompt"], r["max_new_tokens"])
                prev = None
                gaps = []
                for _tok in gen:
                    now = time.monotonic()
                    if prev is None:
                        rec["ttft_s"] = now - t_submit
                    else:
                        gaps.append(now - prev)
                    prev = now
                    rec["tokens"] += 1
                rec["gaps"] = gaps
                rec["t_last"] = prev if prev is not None else t_submit
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                results.append(rec)

    threads = [threading.Thread(target=client_loop, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout_s
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.1))
    if any(t.is_alive() for t in threads):
        errors.append("client threads timed out")
    total_tokens = sum(r["tokens"] for r in results)
    t_last = max((r["t_last"] for r in results), default=t0)
    wall = max(t_last - t0, 1e-9)
    ttfts = [r["ttft_s"] for r in results if "ttft_s" in r]
    gaps = [g for r in results for g in r.get("gaps", ())]
    return {
        "tokens_total": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 2),
        "requests_done": len(results),
        "ttft_ms": {"p50": _ms(_percentile(ttfts, 50)),
                    "p99": _ms(_percentile(ttfts, 99))},
        "inter_token_ms": {"p50": _ms(_percentile(gaps, 50)),
                           "p99": _ms(_percentile(gaps, 99))},
        "errors": errors,
    }


def _ms(v: Optional[float]) -> Optional[float]:
    return round(v * 1e3, 2) if v is not None else None


def bench(smoke: bool = False, clients: int = 8, requests: int = 24,
          seed: int = 0) -> dict:
    import jax

    import ray_tpu
    from ray_tpu import serve

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    if smoke:
        clients, requests = min(clients, 4), min(requests, 6)
        model = {"vocab_size": 128, "d_model": 32, "n_layers": 2,
                 "n_heads": 4, "head_dim": 8, "d_ff": 64,
                 "max_seq_len": 128, "rotary_dim": 8, "dtype": "float32",
                 "remat_policy": "none"}
        engine = {"decode_slots": clients, "kv_block_size": 8,
                  "max_seq_len": 64, "prefill_chunk": 16}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.02,
                                 prompt_rng=(4, 12), out_rng=(6, 10))
    elif on_tpu:
        model = {"vocab_size": 32000, "d_model": 2048, "n_layers": 8,
                 "n_heads": 16, "head_dim": 128, "d_ff": 8192,
                 "max_seq_len": 2048, "rotary_dim": 64,
                 "dtype": "bfloat16", "remat_policy": "none"}
        engine = {"decode_slots": 32, "kv_block_size": 32,
                  "max_seq_len": 1024, "prefill_chunk": 256}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.05,
                                 prompt_rng=(32, 512), out_rng=(32, 128))
    else:
        # CPU sizing: wide enough that a decode step is weight-stream /
        # gemv bound, so step cost is nearly batch-independent — the
        # same regime a real chip is in at decode batch 1 (MXU idle),
        # which is what continuous batching amortizes. Arrivals are
        # compressed so the queue saturates the slots (the serial
        # baseline queues identically).
        model = {"vocab_size": 1024, "d_model": 256, "n_layers": 2,
                 "n_heads": 4, "head_dim": 32, "d_ff": 1024,
                 "max_seq_len": 256, "rotary_dim": 16,
                 "dtype": "float32", "remat_policy": "none"}
        engine = {"decode_slots": clients, "kv_block_size": 16,
                  "max_seq_len": 128, "prefill_chunk": 32}
        workload = make_workload(requests, clients, seed,
                                 mean_interarrival_s=0.005,
                                 prompt_rng=(8, 24), out_rng=(24, 48))

    ray_tpu.init(num_cpus=max(8, clients + 4), _num_initial_workers=3,
                 ignore_reinit_error=True)
    modes = {}
    stats = {}
    try:
        for mode, slots in (("continuous", engine["decode_slots"]),
                            ("serial", 1)):
            ecfg = dict(engine, decode_slots=slots)
            name = f"llm_{mode}"
            dep = serve.deployment(
                name=name, max_ongoing_requests=4 * clients + 8)(
                    serve.LLMServer)
            serve.run(dep.bind(model=model, engine=ecfg), name=name)
            handle = serve.get_app_handle(name)
            # one throwaway request compiles prefill+decode outside the
            # measured window (admission itself never recompiles)
            list(handle.options(stream=True).generate.remote(
                workload[0]["prompt"][:4], 2))
            modes[mode] = run_load(
                lambda name=name: serve.get_app_handle(name),
                workload, clients)
            stats[mode] = handle.stats.remote().result(timeout_s=60)
            serve.delete(name)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()

    cont, ser = modes["continuous"], modes["serial"]
    n_chips = 1   # the engine decodes on one device
    vs_serial = (round(cont["tokens_per_s"] / ser["tokens_per_s"], 2)
                 if ser["tokens_per_s"] else None)
    return {
        "metric": "serve_tokens_per_s_chip",
        "value": round(cont["tokens_per_s"] / n_chips, 2),
        "unit": "tokens/s/chip",
        "vs_serial": vs_serial,
        "detail": {
            "backend": backend,
            "n_chips": n_chips,
            "clients": clients,
            "requests": requests,
            "seed": seed,
            "model": model,
            "engine": engine,
            "continuous": cont,
            "serial": ser,
            "occupancy_hist": stats["continuous"].get("occupancy_hist"),
            "engine_stats": {m: {k: s.get(k) for k in
                                 ("tokens_total", "decode_steps",
                                  "prefill_chunks", "free_blocks",
                                  "total_blocks")}
                             for m, s in stats.items()},
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny workload for CI (subprocess smoke test)")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    rec = bench(smoke=args.smoke, clients=args.clients,
                requests=args.requests, seed=args.seed)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
